"""Distributed-layer tests on the virtual 8-device CPU mesh (SURVEY.md §4
"multi-node without a real cluster"): mesh construction, psum-assembled
module gathers from row-sharded matrices, the 2-D (perm × row) engine path,
and the multi-test vmap path (Config C)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from netrep_tpu.parallel import mesh as meshmod
from netrep_tpu.parallel import sharded
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.parallel.multitest import MultiTestEngine
from netrep_tpu.utils.config import EngineConfig

from test_engine import _make_setup


def test_make_mesh_shapes():
    m = meshmod.make_mesh()
    assert m.shape == {"perm": 8, "row": 1}
    m2 = meshmod.make_mesh(n_row_shards=4)
    assert m2.shape == {"perm": 2, "row": 4}
    with pytest.raises(ValueError, match="not divisible"):
        meshmod.make_mesh(n_row_shards=3)
    with pytest.raises(ValueError, match="needs"):
        meshmod.make_mesh(n_perm_shards=5, n_row_shards=4)


def test_sharded_gather_matches_dense(rng):
    n, m_sz = 64, 9
    mesh = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    mat = rng.standard_normal((n, n))
    mat2 = rng.standard_normal((n, n))
    corr = sharded.shard_rows(jnp.asarray(mat, jnp.float32), mesh)
    net = sharded.shard_rows(jnp.asarray(mat2, jnp.float32), mesh)

    idx = rng.choice(n, size=(3, 5, m_sz), replace=True).astype(np.int32)
    gather = sharded.make_sharded_gatherer(mesh)
    sub_c, sub_n = jax.jit(lambda i: gather(corr, net, i))(jnp.asarray(idx))
    for a in range(3):
        for b in range(5):
            np.testing.assert_allclose(
                np.asarray(sub_c)[a, b], mat[np.ix_(idx[a, b], idx[a, b])], atol=1e-6
            )
            np.testing.assert_allclose(
                np.asarray(sub_n)[a, b], mat2[np.ix_(idx[a, b], idx[a, b])], atol=1e-6
            )


def test_pad_square_to_multiple():
    m = np.ones((10, 10))
    p = sharded.pad_square_to_multiple(m, 4)
    assert p.shape == (12, 12)
    assert p[10:].sum() == 0 and p[:, 10:].sum() == 0
    assert sharded.pad_square_to_multiple(m, 5) is m


def test_row_sharded_engine_matches_replicated(setup_pair):
    """Full 2-D mesh (perm × row): row-sharded matrices + sharded permutation
    chunks reproduce the single-device null exactly (same seed contract)."""
    d, t, modules, pool = setup_pair
    ref = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"],
        modules, pool, config=EngineConfig(chunk_size=8, summary_method="eigh"),
    )
    obs_ref = ref.observed()
    nulls_ref, _ = ref.run_null(16, key=21)

    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    eng = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"],
        modules, pool,
        config=EngineConfig(
            chunk_size=8, summary_method="eigh", matrix_sharding="row"
        ),
        mesh=mesh2d,
    )
    np.testing.assert_allclose(eng.observed(), obs_ref, atol=2e-5)
    nulls, done = eng.run_null(16, key=21)
    assert done == 16
    np.testing.assert_allclose(nulls, nulls_ref, atol=2e-5)


def test_row_sharding_requires_mesh(setup_pair):
    d, t, modules, pool = setup_pair
    with pytest.raises(ValueError, match="requires a mesh"):
        PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"],
            modules, pool, config=EngineConfig(matrix_sharding="row"),
        )


def test_multitest_engine_matches_sequential(setup_pair, rng):
    """Config C: vmapped multi-test nulls equal per-pair sequential runs with
    the same key (shared permutation index draws)."""
    d, t, modules, pool = setup_pair
    # second test cohort: same node universe, fresh data
    t2_data = t["data"] + rng.standard_normal(t["data"].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2

    cfg = EngineConfig(chunk_size=8, summary_method="eigh")
    multi = MultiTestEngine(
        d["correlation"], d["network"], d["data"],
        np.stack([t["correlation"], t2_corr]),
        np.stack([t["network"], t2_net]),
        [t["data"], t2_data],
        modules, pool, config=cfg,
    )
    obs = multi.observed()
    nulls, done = multi.run_null(12, key=9)
    assert done == 12 and nulls.shape[0] == 2

    for ti, (tc, tn, td) in enumerate(
        [(t["correlation"], t["network"], t["data"]), (t2_corr, t2_net, t2_data)]
    ):
        seq = PermutationEngine(
            d["correlation"], d["network"], d["data"], tc, tn, td,
            modules, pool, config=cfg,
        )
        np.testing.assert_allclose(obs[ti], seq.observed(), atol=2e-5)
        seq_nulls, _ = seq.run_null(12, key=9)
        np.testing.assert_allclose(nulls[ti], seq_nulls, atol=2e-5)


def test_multitest_ragged_samples(setup_pair, rng):
    """Test cohorts with different sample counts fall back to the per-dataset
    loop but still produce a stacked result."""
    d, t, modules, pool = setup_pair
    t2_data = rng.standard_normal((t["data"].shape[0] + 5, t["data"].shape[1]))
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2
    multi = MultiTestEngine(
        d["correlation"], d["network"], d["data"],
        np.stack([t["correlation"], t2_corr]),
        np.stack([t["network"], t2_net]),
        [t["data"], t2_data],
        modules, pool, config=EngineConfig(chunk_size=8, summary_method="eigh"),
    )
    assert not multi._uniform_samples
    obs = multi.observed()
    assert np.isfinite(obs).all()
    nulls, done = multi.run_null(8, key=1)
    assert done == 8 and np.isfinite(nulls).all()


def test_vmap_tests_via_api(setup_pair, rng):
    """module_preservation(vmap_tests=True) returns per-test results equal to
    the sequential path."""
    from netrep_tpu import module_preservation

    d, t, modules, pool = setup_pair
    t2_data = t["data"] + rng.standard_normal(t["data"].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2

    kw = dict(
        network={"d": _df(d["network"], d["names"]),
                 "t1": _df(t["network"], t["names"]),
                 "t2": _df(t2_net, t["names"])},
        correlation={"d": _df(d["correlation"], d["names"]),
                     "t1": _df(t["correlation"], t["names"]),
                     "t2": _df(t2_corr, t["names"])},
        data={"d": _df(d["data"], d["names"], square=False),
              "t1": _df(t["data"], t["names"], square=False),
              "t2": _df(t2_data, t["names"], square=False)},
        module_assignments=_labels_from_setup(setup_pair),
        discovery="d", test=["t1", "t2"],
        n_perm=10, seed=4,
        config=EngineConfig(chunk_size=8, summary_method="eigh"),
        simplify=False,
    )
    seq = module_preservation(vmap_tests=False, **kw)
    fast = module_preservation(vmap_tests=True, **kw)
    for tn in ("t1", "t2"):
        np.testing.assert_allclose(
            seq["d"][tn].observed, fast["d"][tn].observed, atol=2e-5
        )


def _df(arr, names, square=True):
    import pandas as pd

    if square:
        return pd.DataFrame(arr, index=names, columns=names)
    return pd.DataFrame(arr, columns=names)


def _labels_from_setup(setup_pair):
    d, t, modules, pool = setup_pair
    lab = {nm: "0" for nm in d["names"]}
    for m in modules:
        for i in m.disc_idx:
            lab[d["names"][i]] = m.label
    return lab


@pytest.fixture
def setup_pair(toy_pair):
    return _make_setup(toy_pair)


def test_sharded_gather_mxu_matches_dense(rng):
    """The TPU-fast mxu-mode sharded gather (sorted rows + one-hot matmuls +
    psum, VERDICT r1 item 3) is exact on the CPU mesh, including duplicate
    and zero-padded indices, with and without a batched perm axis."""
    n, m_sz = 64, 9
    mesh = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    mat = rng.standard_normal((n, n))
    mat2 = rng.standard_normal((n, n))
    corr = sharded.shard_rows(jnp.asarray(mat, jnp.float32), mesh)
    net = sharded.shard_rows(jnp.asarray(mat2, jnp.float32), mesh)

    idx = rng.choice(n, size=(4, 5, m_sz), replace=True).astype(np.int32)
    idx[0, 0, -3:] = 0  # zero-padding pattern the engine produces
    gather = sharded.make_sharded_gatherer(
        mesh, batch_axis="perm", mode="mxu", perm_batch=2
    )
    sub_c, sub_n = jax.jit(lambda i: gather(corr, net, i))(jnp.asarray(idx))
    for a in range(4):
        for b in range(5):
            np.testing.assert_allclose(
                np.asarray(sub_c)[a, b], mat[np.ix_(idx[a, b], idx[a, b])],
                atol=1e-5,
            )
            np.testing.assert_allclose(
                np.asarray(sub_n)[a, b], mat2[np.ix_(idx[a, b], idx[a, b])],
                atol=1e-5,
            )
    # unbatched (observed-pass shape): (K, m)
    g2 = sharded.make_sharded_gatherer(mesh, None, mode="mxu")
    k_idx = idx[0]
    s_c, _s_n = jax.jit(lambda i: g2(corr, net, i))(jnp.asarray(k_idx))
    for b in range(5):
        np.testing.assert_allclose(
            np.asarray(s_c)[b], mat[np.ix_(k_idx[b], k_idx[b])], atol=1e-5
        )
    with pytest.raises(ValueError, match="mode"):
        sharded.make_sharded_gatherer(mesh, mode="mxu-fast")


def test_row_sharded_engine_mxu_gather_matches_replicated(setup_pair):
    """Row-sharded engine with gather_mode='mxu' (the TPU configuration —
    the old code forced 'direct' whenever row-sharded) reproduces the
    replicated single-device null."""
    d, t, modules, pool = setup_pair
    ref = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"],
        modules, pool, config=EngineConfig(chunk_size=8, summary_method="eigh"),
    )
    obs_ref = ref.observed()
    nulls_ref, _ = ref.run_null(16, key=21)

    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    eng = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"],
        modules, pool,
        config=EngineConfig(
            chunk_size=8, summary_method="eigh", matrix_sharding="row",
            gather_mode="mxu",
        ),
        mesh=mesh2d,
    )
    assert eng.gather_mode == "mxu"
    np.testing.assert_allclose(eng.observed(), obs_ref, atol=2e-5)
    nulls, done = eng.run_null(16, key=21)
    assert done == 16
    np.testing.assert_allclose(nulls, nulls_ref, atol=2e-5)


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_multitest_row_sharded_matches_replicated(setup_pair, rng):
    """Config C × Config D (VERDICT r1 item 7): the multi-test vmap path
    with row-sharded matrices runs end-to-end on the 2-D mesh and equals the
    replicated multi-test run exactly (shared permutation-draw contract)."""
    d, t, modules, pool = setup_pair
    t2_data = t["data"] + rng.standard_normal(t["data"].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2

    cfg_rep = EngineConfig(chunk_size=8, summary_method="eigh")
    stack_args = (
        d["correlation"], d["network"], d["data"],
        np.stack([t["correlation"], t2_corr]),
        np.stack([t["network"], t2_net]),
        [t["data"], t2_data],
        modules, pool,
    )
    ref = MultiTestEngine(*stack_args, config=cfg_rep)
    obs_ref = ref.observed()
    nulls_ref, _ = ref.run_null(12, key=9)

    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    for mode in ("direct", "mxu"):
        cfg_row = EngineConfig(
            chunk_size=8, summary_method="eigh", matrix_sharding="row",
            gather_mode=mode,
        )
        eng = MultiTestEngine(*stack_args, config=cfg_row, mesh=mesh2d)
        assert eng.row_sharded
        np.testing.assert_allclose(eng.observed(), obs_ref, atol=2e-5)
        nulls, done = eng.run_null(12, key=9)
        assert done == 12
        np.testing.assert_allclose(nulls, nulls_ref, atol=2e-5)


def test_module_preservation_vmap_tests_row_sharded(setup_pair, rng):
    """User surface: vmap_tests=True + matrix_sharding='row' runs the vmapped
    multi-cohort path (no fallback) and matches the unsharded result."""
    from netrep_tpu import module_preservation

    d, t, modules, pool = setup_pair
    n_d, n_t = d["network"].shape[0], t["network"].shape[0]
    d_names = [f"g{i}" for i in range(n_d)]
    t_names = [f"g{i}" for i in range(n_t)]
    labels = {nm: "0" for nm in d_names}
    for m in modules:
        for i in m.disc_idx:
            labels[d_names[i]] = m.label

    try:
        import pandas as pd
    except Exception:
        pytest.skip("pandas required")
    mk = lambda mat, names: pd.DataFrame(mat, index=names, columns=names)
    dfd = lambda mat, names: pd.DataFrame(mat, columns=names)
    t2_data = t["data"] + rng.standard_normal(t["data"].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2

    kwargs = dict(
        network={"d": mk(d["network"], d_names), "t1": mk(t["network"], t_names),
                 "t2": mk(t2_net, t_names)},
        data={"d": dfd(d["data"], d_names), "t1": dfd(t["data"], t_names),
              "t2": dfd(t2_data, t_names)},
        correlation={"d": mk(d["correlation"], d_names),
                     "t1": mk(t["correlation"], t_names),
                     "t2": mk(t2_corr, t_names)},
        module_assignments=labels,
        discovery="d", test=["t1", "t2"], n_perm=12, seed=5,
        vmap_tests=True,
    )
    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    res_row = module_preservation(
        **kwargs,
        config=EngineConfig(chunk_size=8, summary_method="eigh",
                            matrix_sharding="row"),
        mesh=mesh2d,
    )
    res_rep = module_preservation(
        **kwargs, config=EngineConfig(chunk_size=8, summary_method="eigh"),
    )
    for tname in ("t1", "t2"):
        np.testing.assert_allclose(
            res_row[tname].nulls, res_rep[tname].nulls, atol=2e-5
        )
        np.testing.assert_allclose(
            res_row[tname].observed, res_rep[tname].observed, atol=2e-5
        )


def test_multitest_row_sharded_ragged_samples(setup_pair, rng):
    """Row-sharded multi-test with cohorts of DIFFERENT sample counts: the
    per-dataset list data path and the T-loop chunk program compose, and
    results match the replicated ragged run."""
    d, t, modules, pool = setup_pair
    t2_data = rng.standard_normal((t["data"].shape[0] + 7, t["data"].shape[1]))
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2
    stack_args = (
        d["correlation"], d["network"], d["data"],
        np.stack([t["correlation"], t2_corr]),
        np.stack([t["network"], t2_net]),
        [t["data"], t2_data],
        modules, pool,
    )
    ref = MultiTestEngine(
        *stack_args, config=EngineConfig(chunk_size=8, summary_method="eigh")
    )
    nulls_ref, _ = ref.run_null(8, key=2)

    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    eng = MultiTestEngine(
        *stack_args,
        config=EngineConfig(chunk_size=8, summary_method="eigh",
                            matrix_sharding="row", gather_mode="mxu"),
        mesh=mesh2d,
    )
    np.testing.assert_allclose(eng.observed(), ref.observed(), atol=2e-5)
    nulls, done = eng.run_null(8, key=2)
    assert done == 8
    np.testing.assert_allclose(nulls, nulls_ref, atol=2e-5)


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_derived_network_row_sharded_and_multitest(setup_pair, rng):
    """network_from_correlation composes with row sharding (single-matrix
    collective gather + on-device derivation) and with the multi-test vmap
    path (per-cohort check, shared permutation draws)."""
    d, t, modules, pool = setup_pair
    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)

    ref = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"], modules, pool,
        config=EngineConfig(chunk_size=8, summary_method="eigh"),
    )
    nulls_ref, _ = ref.run_null(16, key=6)
    obs_ref = ref.observed()

    eng = PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"], modules, pool,
        config=EngineConfig(chunk_size=8, summary_method="eigh",
                            matrix_sharding="row", gather_mode="mxu",
                            network_from_correlation=2.0),
        mesh=mesh2d,
    )
    assert eng._test_net is None
    np.testing.assert_allclose(eng.observed(), obs_ref, atol=2e-5)
    nulls, done = eng.run_null(16, key=6)
    assert done == 16
    np.testing.assert_allclose(nulls, nulls_ref, atol=2e-5)

    # multi-test: second cohort with net == |corr|**2 by construction
    t2_data = t["data"] + rng.standard_normal(t["data"].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2
    np.fill_diagonal(t2_net, 1.0)
    stack = (
        d["correlation"], d["network"], d["data"],
        np.stack([t["correlation"], t2_corr]),
        np.stack([t["network"], t2_net]),
        [t["data"], t2_data],
        modules, pool,
    )
    cfg = EngineConfig(chunk_size=8, summary_method="eigh")
    m_ref = MultiTestEngine(*stack, config=cfg)
    m_der = MultiTestEngine(
        *stack,
        config=EngineConfig(chunk_size=8, summary_method="eigh",
                            network_from_correlation=2.0),
    )
    assert m_der._tn is None
    np.testing.assert_allclose(m_der.observed(), m_ref.observed(), atol=2e-5)
    a, _ = m_der.run_null(12, key=8)
    b, _ = m_ref.run_null(12, key=8)
    np.testing.assert_allclose(a, b, atol=2e-5)

    # wrong cohort: multitest checks EVERY dataset
    bad_net = np.abs(t2_corr) ** 4
    with pytest.raises(ValueError, match="test\\[1\\]"):
        MultiTestEngine(
            d["correlation"], d["network"], d["data"],
            np.stack([t["correlation"], t2_corr]),
            np.stack([t["network"], bad_net]),
            [t["data"], t2_data],
            modules, pool,
            config=EngineConfig(network_from_correlation=2.0),
        )


def test_derived_network_multitest_row_sharded(setup_pair, rng):
    """The triple composition: derived network x row sharding x multi-test."""
    d, t, modules, pool = setup_pair
    t2_data = t["data"] + rng.standard_normal(t["data"].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2
    np.fill_diagonal(t2_net, 1.0)
    stack = (
        d["correlation"], d["network"], d["data"],
        np.stack([t["correlation"], t2_corr]),
        np.stack([t["network"], t2_net]),
        [t["data"], t2_data],
        modules, pool,
    )
    ref = MultiTestEngine(
        *stack, config=EngineConfig(chunk_size=8, summary_method="eigh")
    )
    nulls_ref, _ = ref.run_null(8, key=13)

    mesh2d = meshmod.make_mesh(n_perm_shards=2, n_row_shards=4)
    eng = MultiTestEngine(
        *stack,
        config=EngineConfig(chunk_size=8, summary_method="eigh",
                            matrix_sharding="row", gather_mode="mxu",
                            network_from_correlation=2.0),
        mesh=mesh2d,
    )
    assert eng._tn is None
    np.testing.assert_allclose(eng.observed(), ref.observed(), atol=2e-5)
    nulls, done = eng.run_null(8, key=13)
    assert done == 8
    np.testing.assert_allclose(nulls, nulls_ref, atol=2e-5)
