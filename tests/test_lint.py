"""Invariant-linter tests (ISSUE 12).

Three layers:

1. every rule FIRES on a minimal violating fixture (a rule that cannot
   fire is a disabled contract);
2. the suppression grammar is honored AND tallied (a justified exception
   is counted, a reasonless one is itself a finding);
3. the tier-1 gate: the package itself lints clean — zero unsuppressed
   findings over ``netrep_tpu/`` with all rules active, so any commit
   that violates a contract must fix or justify it in the same diff.
"""

import json
import subprocess
import sys

import pytest

from netrep_tpu.analysis import default_rules, lint_paths, lint_source
from netrep_tpu.analysis.linter import SYNTAX_RULE

RULE_NAMES = tuple(r.name for r in default_rules())


def findings_by_rule(report):
    out = {}
    for f in report.findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---------------------------------------------------------------------------
# per-rule violating fixtures — every rule must fire
# ---------------------------------------------------------------------------

RNG_BAD = """\
import jax

def chunk_keys(seed):
    key = jax.random.PRNGKey(seed)
    return jax.random.split(key, 4)
"""

RNG_HOST_BAD = """\
import time
import numpy as np

def jitter():
    return np.random.default_rng().random() + time.time()
"""

RNG_OK = """\
import jax

def perm(key, i, pool):
    k = jax.random.fold_in(key, i)
    return jax.random.permutation(k, pool)
"""

DONATE_BAD = """\
import jax
from jax.experimental import pallas as pl

def jit_program(fn):
    return jax.jit(fn, donate_argnums=(0,))
"""

DONATE_OK_GATED = """\
import jax
from jax.experimental import pallas as pl

def jit_program(fn, stat_mode):
    donate = () if stat_mode == "fused" else (0,)
    return jax.jit(fn, donate_argnums=donate)
"""

EXC_BAD = """\
def f(work):
    try:
        work()
    except Exception:
        pass
"""

EXC_OK_RERAISE = """\
def f(work, pool, key):
    try:
        work()
    except BaseException:
        pool.discard(key)
        raise
"""

EXC_OK_CLASSIFY = """\
from netrep_tpu.utils.faults import classify_error

def f(work):
    try:
        work()
    except Exception as e:
        classify_error(e)
"""

TEL_BAD = """\
def f(tel):
    tel.emit("definitely_not_a_registered_event", n=1)
"""

TEL_END_SPAN_BAD = """\
def f(tel, sid):
    tel.end_span(sid, "bogus_run_end", s=1.0)
"""

TEL_OK = """\
def f(tel, sid):
    tel.emit("chunk", perms=64)
    tel.end_span(sid, "null_run_end", s=1.0)
"""

SPAN_BAD = """\
def run(tel):
    sid = tel.begin_span("null_run_start", n_perm=64)
    work()
"""

SPAN_BAD_CLASS = """\
class Server:
    def boot(self, tel):
        self._sid = tel.begin_span("serve_start")
"""

SPAN_OK = """\
def run(tel):
    sid = tel.begin_span("null_run_start", n_perm=64)
    work()
    tel.end_span(sid, "null_run_end", s=1.0)
"""

SPAN_OK_CLASS_HANDOFF = """\
class Server:
    def boot(self, tel):
        self.tel = tel
        self._sid = tel.begin_span("serve_start")

    def close(self):
        self.tel.end_span(self._sid, "serve_end", s=1.0)
"""

CKPT_BAD_PREFIX = """\
from netrep_tpu.utils.checkpoint import save_null_checkpoint

def save(path, nulls, kd, fp):
    save_null_checkpoint(path, nulls, 4, kd, fp,
                         extra={"x_tallies": nulls})
"""

CKPT_BAD_RESERVED = """\
from netrep_tpu.utils.checkpoint import save_null_checkpoint

def save(path, nulls, kd, fp):
    save_null_checkpoint(path, nulls, 4, kd, fp,
                         extra={"completed": nulls})
"""

CKPT_OK = """\
from netrep_tpu.utils.checkpoint import save_null_checkpoint

def save(path, nulls, kd, fp):
    save_null_checkpoint(path, nulls, 4, kd, fp,
                         extra={"stream_hi": nulls})
"""

AUTOKEY_BAD = """\
class Eng:
    def autotune_key(self, extra=""):
        return f"{self.gather_mode}|{extra}"
"""

AUTOKEY_OK_DELEGATES = """\
class Packed(Base):
    def autotune_key(self, extra=""):
        return super().autotune_key(extra=f"packed|{extra}")
"""

THREAD_BAD = """\
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        self._n += 1

    def count(self):
        return self._n
"""

THREAD_OK_GUARDED = """\
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        with self._lock:
            self._n += 1

    def count(self):
        with self._lock:
            return self._n
"""

THREAD_TRANSITIVE_BAD = """\
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._state = None
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        self._step()

    def _step(self):
        self._state = "running"

    def peek(self):
        return self._state
"""


@pytest.mark.parametrize("rule,source,min_hits", [
    ("rng-discipline", RNG_BAD, 2),
    ("rng-discipline", RNG_HOST_BAD, 2),
    ("donation-alias", DONATE_BAD, 1),
    ("exception-taxonomy", EXC_BAD, 1),
    ("telemetry-registry", TEL_BAD, 1),
    ("telemetry-registry", TEL_END_SPAN_BAD, 1),
    ("span-pairing", SPAN_BAD, 1),
    ("span-pairing", SPAN_BAD_CLASS, 1),
    ("checkpoint-extras-namespace", CKPT_BAD_PREFIX, 1),
    ("checkpoint-extras-namespace", CKPT_BAD_RESERVED, 1),
    ("checkpoint-extras-namespace", AUTOKEY_BAD, 1),
    ("thread-shared-state", THREAD_BAD, 2),
])
def test_rule_fires_on_violating_fixture(rule, source, min_hits):
    report = lint_source(source)
    hits = findings_by_rule(report).get(rule, [])
    assert len(hits) >= min_hits, report.render()
    # the finding carries a real location, not a placeholder
    assert all(f.line >= 1 and f.path for f in hits)
    assert not report.ok


@pytest.mark.parametrize("source", [
    RNG_OK, DONATE_OK_GATED, EXC_OK_RERAISE, EXC_OK_CLASSIFY, TEL_OK,
    SPAN_OK, SPAN_OK_CLASS_HANDOFF, CKPT_OK, AUTOKEY_OK_DELEGATES,
    THREAD_OK_GUARDED,
])
def test_compliant_fixture_is_clean(source):
    report = lint_source(source)
    assert report.ok, report.render()


def test_thread_rule_sees_through_helper_calls():
    """A helper invoked from the worker loop executes on the worker
    thread — the transitive-closure half of the lightweight analysis."""
    report = lint_source(THREAD_TRANSITIVE_BAD)
    hits = findings_by_rule(report).get("thread-shared-state", [])
    assert hits, report.render()


# ---------------------------------------------------------------------------
# suppressions: honored, tallied, reason-required
# ---------------------------------------------------------------------------

def _suppress(source: str, rule: str, reason="fixture-sanctioned site"):
    """Prefix every line that would produce a finding with an allow
    comment (same-line form)."""
    base = lint_source(source)
    lines = source.splitlines()
    for f in base.findings:
        if f.rule == rule:
            lines[f.line - 1] += f"  # netrep: allow({rule}) — {reason}"
    return "\n".join(lines) + "\n"


@pytest.mark.parametrize("rule,source", [
    ("rng-discipline", RNG_BAD),
    ("donation-alias", DONATE_BAD),
    ("exception-taxonomy", EXC_BAD),
    ("telemetry-registry", TEL_BAD),
    ("span-pairing", SPAN_BAD),
    ("checkpoint-extras-namespace", CKPT_BAD_PREFIX),
    ("thread-shared-state", THREAD_BAD),
])
def test_suppression_honored_and_tallied(rule, source):
    suppressed_src = _suppress(source, rule)
    report = lint_source(suppressed_src)
    assert report.ok, report.render()
    assert len(report.suppressed) >= 1
    assert all(f.rule == rule for f in report.suppressed)
    # tallied: every honored suppression records its use count + reason
    used = [s for s in report.suppressions if s.used]
    assert used and all(s.reason for s in used)
    assert not report.stale


def test_suppression_comment_above_finding_line():
    src = EXC_BAD.replace(
        "    except Exception:",
        "    # netrep: allow(exception-taxonomy) — fixture: error is "
        "rethrown by the caller\n    except Exception:",
    )
    report = lint_source(src)
    assert report.ok, report.render()
    assert len(report.suppressed) == 1


def test_suppression_without_reason_is_a_finding():
    src = EXC_BAD.replace(
        "    except Exception:",
        "    except Exception:  # netrep: allow(exception-taxonomy)",
    )
    report = lint_source(src)
    rules = {f.rule for f in report.findings}
    # the reasonless allow is flagged AND does not silence the original
    assert SYNTAX_RULE in rules and "exception-taxonomy" in rules


def test_suppression_in_docstring_is_ignored():
    src = (
        '"""Docs may show the grammar: # netrep: allow(x) — reason."""\n'
        "VALUE = 1\n"
    )
    report = lint_source(src)
    assert report.ok, report.render()
    assert not report.suppressions


def test_stale_suppression_reported_not_fatal():
    src = "# netrep: allow(rng-discipline) — nothing here violates it\n" \
          "VALUE = 1\n"
    report = lint_source(src)
    assert report.ok
    assert len(report.stale) == 1


def test_rule_filter_and_unknown_rule():
    report = lint_source(RNG_BAD, rule_names=["donation-alias"])
    assert report.ok  # rng rule inactive, donation rule has nothing
    with pytest.raises(ValueError, match="unknown rule"):
        lint_paths(rule_names=["not-a-rule"])


# ---------------------------------------------------------------------------
# scoping: null-path rules apply to fixtures and to the right subtrees
# ---------------------------------------------------------------------------

def test_rng_scope_limits_to_null_path_subpackages(tmp_path):
    # a package file OUTSIDE parallel/ops/atlas (e.g. utils/) is out of
    # scope for rng-discipline; lint_paths of a real utils file with
    # np.random (selftest.py builds oracle problems) stays clean
    from netrep_tpu.analysis.rules import Module, RngDiscipline

    rule = RngDiscipline()
    src = "import numpy as np\nR = np.random.default_rng(0)\n"
    in_scope = Module("x.py", src, pkg_rel="parallel/x.py")
    out_scope = Module("x.py", src, pkg_rel="utils/x.py")
    fixture = Module("x.py", src, pkg_rel=None)
    assert rule.check(in_scope) and rule.check(fixture)
    assert not rule.check(out_scope)


# ---------------------------------------------------------------------------
# the tier-1 gate: the package itself lints clean
# ---------------------------------------------------------------------------

def test_package_lints_clean_with_all_rules():
    report = lint_paths()
    assert len(report.rules) >= 6
    assert report.ok, "\n" + report.render()
    # acceptance criterion: every inline suppression carries a reason
    assert report.suppressions, "expected sanctioned sites to be tallied"
    assert all(s.reason.strip() for s in report.suppressions)
    # and none of them is stale (a fixed violation must drop its comment)
    assert not report.stale, "\n" + report.render()


def test_cli_lint_json_schema():
    out = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "lint", "--json"],
        capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/tmp"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    row = json.loads(out.stdout.strip().splitlines()[-1])
    assert row["lint_v"] == 1 and row["ok"] is True
    assert set(RULE_NAMES) <= set(row["rules"])
    assert row["findings"] == []
    assert row["suppressions"]


def test_cli_lint_exit_2_on_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(EXC_BAD)
    out = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "lint", str(bad)],
        capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "HOME": "/tmp"},
    )
    assert out.returncode == 2, out.stdout + out.stderr
    assert "exception-taxonomy" in out.stdout
