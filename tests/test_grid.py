"""All-pairs preservation atlas (ISSUE 17) — the one acceptance that
matters is BIT-IDENTITY: every grid cell, however it was produced, must
equal the solo ``module_preservation`` run of the same (discovery, test)
pair with the same seed. Pinned here for each production path:

  * packed + deduped cold grid (cells sharing a test column ride one
    shared dispatch stream; observed stats come from the digest-keyed
    ``ObservedCache``),
  * resumed-from-checkpoint grid (every cell reloaded from the manifest,
    nothing recomputed),
  * digest-incremental re-analysis (one changed cohort → only its
    row+column recomputes, warm-started from the prior run's tallies),
  * fleet-spread grid (cells dispatched across PR 14 replicas),
  * serve-side cross-pair packing (two tenant submissions against one
    test dataset share a pack id).

Plus the :meth:`StopMonitor.seed_priors` contract the warm start rests
on: priors enter the DECISION rules only, reported tallies/p-values stay
fresh-draw-only, the ``min_perms`` floor applies to fresh draws, and the
priors ride the checkpoint state round-trip.
"""

import shutil
import tempfile

import numpy as np
import pytest

from netrep_tpu import grid_preservation, module_preservation
from netrep_tpu.ops.sequential import StopMonitor, StopRule
from netrep_tpu.utils.config import EngineConfig

N, S = 30, 40
NPERM = 64
SEED = 7
CFG = EngineConfig(chunk_size=16, autotune=False)
RULE = StopRule(min_perms=8)
NAMES = ["a", "b", "c"]
#: the 4 computable cells: a/b carry assignments, c is test-only
PAIRS = [("a", "b"), ("a", "c"), ("b", "a"), ("b", "c")]


def _mk(seed):
    r = np.random.default_rng(seed)
    data = r.normal(size=(S, N))
    corr = np.corrcoef(data, rowvar=False)
    return np.abs(corr) ** 2, corr, data


def _cohorts():
    network, correlation, data = {}, {}, {}
    for i, n in enumerate(NAMES):
        network[n], correlation[n], data[n] = _mk(100 + i)
    assign = {
        "a": {f"node_{i}": str(1 + (i % 3)) for i in range(N)},
        "b": {f"node_{i}": str(1 + (i % 4)) for i in range(N)},
    }
    return network, correlation, data, assign


def _solo(network, correlation, data, assign, d, t, *, n_perm=NPERM,
          adaptive=False, priors=None):
    kw = {}
    if adaptive:
        kw = {"adaptive": True, "adaptive_rule": RULE}
        if priors is not None:
            kw["adaptive_priors"] = priors
    return module_preservation(
        network, data=data, correlation=correlation,
        module_assignments=assign[d], discovery=d, test=t,
        n_perm=n_perm, null="all", seed=SEED, config=CFG,
        simplify=False, **kw,
    )[d][t]


def _same_cell(cell, solo):
    return (np.array_equal(cell.observed, solo.observed)
            and np.array_equal(cell.p_values, solo.p_values)
            and np.array_equal(cell.n_perm_used, solo.n_perm_used))


@pytest.fixture(scope="module")
def atlas():
    """One cold adaptive grid in a persistent grid_dir, plus the solo
    adaptive reference for every cell — shared by the cold/resume/delta
    tests (the delta test re-runs into the SAME dir, which is exactly
    the production shape: one atlas directory, successive analyses)."""
    network, correlation, data, assign = _cohorts()
    gdir = tempfile.mkdtemp(prefix="grid_atlas_")
    g = grid_preservation(
        network, data=data, correlation=correlation,
        module_assignments=assign, n_perm=NPERM, null="all", seed=SEED,
        config=CFG, adaptive=True, adaptive_rule=RULE, grid_dir=gdir,
    )
    solo = {
        (d, t): _solo(network, correlation, data, assign, d, t,
                      adaptive=True)
        for d, t in PAIRS
    }
    yield g, gdir, (network, correlation, data, assign), solo
    shutil.rmtree(gdir, ignore_errors=True)


def test_cold_grid_cells_bit_identical_to_solo(atlas):
    """Packed + deduped cold grid: every cell equals the solo adaptive
    run — p-values, observed, and realized stopping points all exact."""
    g, _, _, solo = atlas
    for d, t in PAIRS:
        assert _same_cell(g.cell(d, t), solo[(d, t)]), (d, t)
    st = g.stats
    assert st["cells_total"] == len(PAIRS)
    assert st["cells_computed"] == len(PAIRS)
    assert st["cells_reused"] == 0
    assert st["perms_evaluated"] > 0
    # packing happened: cells sharing a test column rode shared streams
    assert st["packs"] < st["cells_computed"]
    # dedup happened: each discovery cohort's observed stats computed
    # once, reused across its row (a->b and a->c share a's digest)
    assert st["dedup"]["hits"] > 0


def test_grid_resume_reuses_every_cell_bit_identically(atlas):
    """Re-running into the same grid_dir reloads every cell from the
    digest-keyed manifest — zero permutations, identical results."""
    g, gdir, (network, correlation, data, assign), solo = atlas
    g2 = grid_preservation(
        network, data=data, correlation=correlation,
        module_assignments=assign, n_perm=NPERM, null="all", seed=SEED,
        config=CFG, adaptive=True, adaptive_rule=RULE, grid_dir=gdir,
    )
    assert g2.stats["cells_reused"] == len(PAIRS)
    assert g2.stats["cells_computed"] == 0
    assert g2.stats["perms_evaluated"] == 0
    for d, t in PAIRS:
        assert _same_cell(g2.cell(d, t), solo[(d, t)]), (d, t)


def test_incremental_delta_recomputes_only_changed_row_and_column(atlas):
    """Changing one cohort's content digest recomputes only the cells
    touching it (warm-started from the prior tallies); untouched cells
    come back from the manifest byte-identical — and the warm-started
    cells still equal the solo run given the same priors."""
    g, gdir, (network, correlation, data, assign), solo = atlas
    network2, correlation2, data2 = (
        dict(network), dict(correlation), dict(data)
    )
    # c is test-only: its change dirties a->c and b->c, leaves a<->b
    network2["c"], correlation2["c"], data2["c"] = _mk(999)
    g3 = grid_preservation(
        network2, data=data2, correlation=correlation2,
        module_assignments=assign, n_perm=NPERM, null="all", seed=SEED,
        config=CFG, adaptive=True, adaptive_rule=RULE, grid_dir=gdir,
    )
    assert g3.stats["cells_computed"] == 2
    assert g3.stats["cells_reused"] == 2
    assert g3.stats["cells_warmstarted"] == 2
    for d, t in [("a", "b"), ("b", "a")]:
        assert _same_cell(g3.cell(d, t), solo[(d, t)]), (d, t)
    # warm-started cell == solo module_preservation fed the same priors
    for d in ["a", "b"]:
        prev = g.cell(d, "c")
        priors = (np.asarray(prev.counts_hi, np.int64),
                  np.asarray(prev.counts_lo, np.int64),
                  np.asarray(prev.n_perm_used, np.int64))
        want = _solo(network2, correlation2, data2, assign, d, "c",
                     adaptive=True, priors=priors)
        assert _same_cell(g3.cell(d, "c"), want), d


def test_fleet_spread_cells_bit_identical_to_solo(tmp_path):
    """Cells dispatched across an in-process 2-replica fleet (PR 14)
    return the same bytes as the local solo runs."""
    from netrep_tpu.serve.fleet import build_inprocess_fleet
    from netrep_tpu.serve.scheduler import ServeConfig

    network, correlation, data, assign = _cohorts()
    n_perm = 48

    def make_config(rid, jpath, ckpt):
        return ServeConfig(journal=jpath, checkpoint_dir=ckpt,
                           fleet_label=rid, engine=CFG, null="all")

    coord = build_inprocess_fleet(2, str(tmp_path), make_config=make_config)
    try:
        g = grid_preservation(
            network, data=data, correlation=correlation,
            module_assignments=assign, n_perm=n_perm, null="all",
            seed=SEED, config=CFG, fleet=coord,
        )
        for d, t in PAIRS:
            want = _solo(network, correlation, data, assign, d, t,
                         n_perm=n_perm)
            cell = g.cell(d, t)
            assert np.array_equal(cell.observed, want.observed), (d, t)
            assert np.array_equal(cell.p_values, want.p_values), (d, t)
    finally:
        coord.close()


def test_serve_cross_pair_packing_shares_pack_and_matches_solo():
    """Two tenant submissions against the same test dataset inside the
    pack window ride ONE shared dispatch stream (same pack id, size 2)
    and still return solo-identical numbers — the two-identity contract
    of the cross-pair packer."""
    from netrep_tpu.serve.scheduler import PreservationServer, ServeConfig

    network, correlation, data, assign = _cohorts()
    n_perm = 48
    srv = PreservationServer(ServeConfig(
        engine=CFG, null="all", cross_pair_packing=True,
        pack_window_s=0.3,
    ), start=False)
    srv.register_tenant("t")
    for n in NAMES:
        srv.register_dataset("t", n, network=network[n],
                             correlation=correlation[n], data=data[n],
                             assignments=assign.get(n))
    h1 = srv.submit("t", "a", "c", n_perm=n_perm, seed=SEED)
    h2 = srv.submit("t", "b", "c", n_perm=n_perm, seed=SEED)
    srv.start()
    try:
        r1 = srv.wait(h1)
        r2 = srv.wait(h2)
    finally:
        srv.close()
    assert r1["pack_id"] == r2["pack_id"]
    assert r1["pack_size"] == 2 and r2["pack_size"] == 2
    for d, r in (("a", r1), ("b", r2)):
        want = _solo(network, correlation, data, assign, d, "c",
                     n_perm=n_perm)
        assert np.array_equal(r["observed"], want.observed), d
        assert np.array_equal(r["p_values"], want.p_values), d


# -- seed_priors contract (the warm start's statistical foundation) ------


def _monitor(rule=None):
    obs = np.array([[0.5, 0.5], [0.5, 0.5]])
    return StopMonitor(obs, "greater", rule or RULE)


def test_seed_priors_validation():
    m = _monitor()
    with pytest.raises(ValueError, match="non-negative"):
        m.seed_priors(np.full((2, 2), -1), np.zeros((2, 2)), np.zeros(2))
    with pytest.raises(ValueError, match="shapes"):
        m.seed_priors(np.zeros((3, 2)), np.zeros((3, 2)), np.zeros(3))
    with pytest.raises(ValueError, match="shape"):
        m.seed_priors(np.zeros((2, 2)), np.zeros((2, 2)), np.zeros(3))
    # priors folded mid-run would make decisions depend on call order
    m.update(np.full((4, 2, 2), 9.0), 4)
    with pytest.raises(ValueError, match="before any chunk"):
        m.seed_priors(np.zeros((2, 2)), np.zeros((2, 2)), np.zeros(2))


def test_seed_priors_decide_early_but_report_fresh_only():
    """A cell whose prior run clearly exceeded alpha retires after the
    min_perms FRESH floor instead of re-earning the full Besag–Clifford
    budget — while its reported tallies, n_used, and p-values count the
    fresh draws exclusively."""
    rule = StopRule(min_perms=8, h=16)
    warm = _monitor(rule)
    cold = _monitor(rule)
    # prior: 200 draws, every one exceeding (p clearly >> alpha)
    warm.seed_priors(np.full((2, 2), 200), np.zeros((2, 2)),
                     np.full(2, 200))
    # ambiguous fresh chunk: 2 of 8 draws exceed — fresh hi=2 < h=16 and
    # the CP interval for 2/8 straddles alpha, so fresh-only can't decide
    chunk = np.full((8, 2, 2), -9.0)
    chunk[:2] = 9.0
    retired_warm = warm.update(chunk, 8)
    retired_cold = cold.update(chunk, 8)
    # warm: h rule fires at min_perms via the pooled counts (2+200 >= 16)
    assert retired_warm.tolist() == [0, 1]
    assert retired_cold.size == 0
    # reported state is fresh-only: priors never leak into the tallies
    assert warm.hi.tolist() == [[2, 2], [2, 2]]
    assert warm.n_used.tolist() == [8, 8]
    assert np.array_equal(warm.hi, cold.hi)


def test_seed_priors_respect_min_perms_floor():
    """Even an overwhelming prior cannot retire a module before the
    fresh-draw floor — every warm-started cell samples the NEW data."""
    rule = StopRule(min_perms=8, h=16)
    m = _monitor(rule)
    m.seed_priors(np.full((2, 2), 10_000), np.zeros((2, 2)),
                  np.full(2, 10_000))
    assert m.update(np.full((4, 2, 2), 9.0), 4).size == 0  # n=4 < floor
    assert m.active.all()
    assert m.update(np.full((4, 2, 2), 9.0), 4).tolist() == [0, 1]


def test_seed_priors_ride_checkpoint_state_roundtrip():
    """An interrupted warm-started run must resume with identical
    decisions: the priors travel in the seq_prior_* checkpoint keys."""
    rule = StopRule(min_perms=8, h=16)
    m = _monitor(rule)
    hi = np.full((2, 2), 200, dtype=np.int64)
    m.seed_priors(hi, np.zeros((2, 2), np.int64), np.full(2, 200))
    state = {k: np.copy(v) for k, v in m.state_arrays().items()}
    assert "seq_prior_n" in state
    m2 = _monitor(rule)
    m2.restore_state(state)
    assert np.array_equal(m2.prior_hi, hi)
    assert np.array_equal(m2.prior_n, np.full(2, 200))
    # and the restored monitor decides exactly like the original
    a = m.update(np.full((8, 2, 2), 9.0), 8)
    b = m2.update(np.full((8, 2, 2), 9.0), 8)
    assert np.array_equal(a, b) and a.tolist() == [0, 1]
