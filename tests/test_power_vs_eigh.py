"""Power-iteration vs exact-eigh drift at bench shapes (VERDICT r1 item 9).

The engine computes OBSERVED statistics with ``summary_method='eigh'``
(one-shot, exact) but NULL statistics with masked power iteration
(``power_iters`` fixed for jit; SURVEY.md §7 "Batched SVD on TPU") — two
numerics for the same statistic. These tests bound the drift at the
north-star module scale (m≈200, s=128, f32) and are the evidence behind the
``EngineConfig.power_iters`` default:

- *Structured* modules (a planted factor, even two near-equal factors —
  gram gap ratio ≈ 0.96): power-60 matches eigh to ~1e-5 on every
  statistic, because convergence is geometric in the gram eigenvalue ratio.
- *Null-like* modules (random node sets — the actual null draws): the gram
  spectrum is a Marchenko–Pastur bulk with top-eigenvalue ratios ≈ 1, so
  the power PROFILE never converges to the principal eigenvector. That is
  harmless by symmetry: an unconverged profile is a random direction in the
  top subspace exactly as the exact one is across draws, so the null
  DISTRIBUTION of profile statistics is invariant (checked below); the one
  systematic effect is coherence biased low by ≲5e-4 absolute (≈2% of the
  null mean, far under the null sd), measured here and asserted.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from netrep_tpu.ops.stats import (
    make_disc_props,
    module_stats_masked,
    standardize_masked,
)

S, M = 128, 200  # bench-shaped module: ~200 nodes, 128 samples
COH = 1  # STAT_NAMES order: avg.weight, coherence, cor.cor, cor.degree, ...


def _module_stats(data_d, data_t, method, n_iter):
    """Seven statistics for one module where discovery=planted props (always
    eigh, like the engine's one-shot bucket build) and the test side uses
    ``method`` — mirroring the engine's observed/null numerics split."""
    corr_d = np.corrcoef(data_d, rowvar=False).astype(np.float32)
    net_d = (np.abs(corr_d) ** 2).astype(np.float32)
    corr_t = np.corrcoef(data_t, rowvar=False).astype(np.float32)
    net_t = (np.abs(corr_t) ** 2).astype(np.float32)
    mask = jnp.ones(M, jnp.float32)
    disc = make_disc_props(
        jnp.asarray(corr_d), jnp.asarray(net_d),
        jnp.asarray(data_d, jnp.float32), mask,
    )
    z = standardize_masked(jnp.asarray(data_t, jnp.float32), mask)
    out = module_stats_masked(
        disc, jnp.asarray(corr_t), jnp.asarray(net_t), z,
        n_iter=n_iter, summary_method=method,
    )
    return np.asarray(out, np.float64)


def test_structured_module_power_matches_eigh():
    rng = np.random.default_rng(1)
    lat = rng.standard_normal(S)
    mk = lambda: rng.standard_normal((S, M)) * 0.8 + lat[:, None]
    d, t = mk(), mk()
    p = _module_stats(d, t, "power", 60)
    e = _module_stats(d, t, "eigh", 60)
    np.testing.assert_allclose(p, e, atol=3e-4, rtol=1e-3)


def test_near_degenerate_module_power_matches_eigh():
    """Two planted factors at strength ratio 0.98 — the adversarial case for
    power iteration (gram gap ratio 0.98² ≈ 0.96 → error ~0.96^60 ≈ 0.09
    of the initial off-axis component, further attenuated by the start
    vector's alignment)."""
    rng = np.random.default_rng(2)
    l1, l2 = rng.standard_normal(S), rng.standard_normal(S)

    def mk():
        x = rng.standard_normal((S, M)) * 0.5
        x[:, : M // 2] += 1.00 * l1[:, None]
        x[:, M // 2:] += 0.98 * l2[:, None]
        return x

    d, t = mk(), mk()
    p = _module_stats(d, t, "power", 60)
    e = _module_stats(d, t, "eigh", 60)
    np.testing.assert_allclose(p, e, atol=1e-3, rtol=2e-3)


def test_null_like_modules_distribution_parity():
    """Random modules (what permutation nulls actually evaluate): per-draw
    profiles differ between the numerics, but every topology statistic is
    exactly shared, and the profile statistics' null DISTRIBUTION moments
    must agree — coherence within its measured ≲5e-4 systematic bias, the
    contribution statistics to Monte-Carlo error."""
    rng = np.random.default_rng(3)
    draws = 30
    P = np.empty((draws, 7))
    E = np.empty((draws, 7))
    for i in range(draws):
        d = rng.standard_normal((S, M))
        t = rng.standard_normal((S, M))
        P[i] = _module_stats(d, t, "power", 60)
        E[i] = _module_stats(d, t, "eigh", 60)
    # topology statistics don't touch the profile: identical numerics
    np.testing.assert_allclose(P[:, [0, 2, 3]], E[:, [0, 2, 3]], atol=1e-6)
    # coherence: small systematic underestimate by unconverged power, bounded
    dcoh = P[:, COH] - E[:, COH]
    assert np.abs(dcoh).max() < 2e-3
    assert abs(dcoh.mean()) < 7.5e-4   # the measured ≈4e-4 bias, with slack
    # null-distribution parity of the profile statistics (cor.contrib=4,
    # avg.cor=5 shares no profile → exact; avg.contrib=6): means agree to
    # Monte-Carlo error of `draws` null draws
    for j in (4, 6):
        se = (P[:, j].std() + E[:, j].std()) / np.sqrt(draws) + 1e-9
        assert abs(P[:, j].mean() - E[:, j].mean()) < 4 * se
    np.testing.assert_allclose(P[:, 5], E[:, 5], atol=1e-6)  # avg.cor
