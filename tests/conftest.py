"""Test bootstrap: force JAX onto a virtual 8-device CPU platform *before*
jax is imported anywhere (SURVEY.md §4: host-platform device-count trick so
pjit/shard_map paths run in CI without TPU hardware), and make the repo root
importable."""

import os
import sys

# Force (not setdefault): the driver environment pins JAX_PLATFORMS=axon
# (the real-TPU tunnel); unit tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_NUM_CPU_DEVICES", "8")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The driver image's sitecustomize imports jax at interpreter startup to
# register the axon (TPU tunnel) backend, which snapshots JAX_PLATFORMS=axon
# before this file runs — so the env var alone is not enough: update the
# live config too, or every jax.devices() call tries to dial the tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite's wall-clock is dominated by
# repeated XLA compiles of 8-device shard_map programs (round-2 measurement:
# 785 s on 4 workers, mostly compile). Cache survives across runs/workers in
# a gitignored repo-local dir; min-compile-time 0.5 s keeps tiny programs out.
from netrep_tpu.utils.backend import enable_persistent_cache  # noqa: E402

enable_persistent_cache(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


# The synthetic fixture generator is a public API now (the reference ships
# bundled example data, SURVEY.md §2.1); tests use the same code path.
from netrep_tpu.data import make_example_pair as make_toy_pair  # noqa: E402


@pytest.fixture
def toy_pair(rng):
    return make_toy_pair(rng)


@pytest.fixture(scope="module")
def toy_pair_module():
    return make_toy_pair(np.random.default_rng(42))


@pytest.fixture(scope="session")
def toy_pair_session():
    return make_toy_pair(np.random.default_rng(42))


# The one shared copy of the pandas-packaging transform lives in
# netrep_tpu.data (review r5 deduplicated it here; ADVICE r5 moved it again
# — `from conftest import ...` in test modules breaks under
# importmode=importlib, while package imports are path-stable anywhere).
from netrep_tpu.data import pair_frames  # noqa: E402, F401


@pytest.fixture(scope="session")
def result(toy_pair_session):
    """One full module_preservation run shared by every API-surface test
    (session scope: the engine pass is the suite's unit of expensive work)."""
    from netrep_tpu import module_preservation
    from netrep_tpu.utils.config import EngineConfig

    d, t = pair_frames(toy_pair_session)
    return module_preservation(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=dict(toy_pair_session["labels"]),
        discovery="disc",
        test="test",
        n_perm=250,
        seed=123,
        config=EngineConfig(chunk_size=64, summary_method="power",
                            power_iters=50),
    )
