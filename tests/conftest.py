"""Test bootstrap: force JAX onto a virtual 8-device CPU platform *before*
jax is imported anywhere (SURVEY.md §4: host-platform device-count trick so
pjit/shard_map paths run in CI without TPU hardware), and make the repo root
importable."""

import os
import sys

# Force (not setdefault): the driver environment pins JAX_PLATFORMS=axon
# (the real-TPU tunnel); unit tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_NUM_CPU_DEVICES", "8")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The driver image's sitecustomize imports jax at interpreter startup to
# register the axon (TPU tunnel) backend, which snapshots JAX_PLATFORMS=axon
# before this file runs — so the env var alone is not enough: update the
# live config too, or every jax.devices() call tries to dial the tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def make_toy_pair(
    rng,
    n_disc=90,
    n_test=80,
    n_overlap=70,
    n_samples_disc=40,
    n_samples_test=35,
    module_sizes=(15, 12, 10, 8),
    noise=0.7,
):
    """Synthetic discovery/test co-expression pair in the spirit of the
    reference's vignette toy data (SURVEY.md §2.1 "Example data",
    BASELINE.json:7): planted correlated modules shared by both datasets,
    with partial node overlap and shuffled test-node order.

    Returns a dict with data/correlation/network matrices per dataset, node
    name lists, and the discovery module-label vector (module labels "1".."K",
    background "0").
    """
    names_disc = [f"g{i:04d}" for i in range(n_disc)]
    # test shares the first n_overlap discovery nodes plus its own extras,
    # in shuffled order so index alignment is exercised.
    extra = [f"t{i:04d}" for i in range(n_test - n_overlap)]
    names_test = list(rng.permutation(names_disc[:n_overlap] + extra))

    labels = np.zeros(n_disc, dtype=object)
    pos = 0
    latents = {}
    for k, sz in enumerate(module_sizes, start=1):
        labels[pos: pos + sz] = str(k)
        latents[str(k)] = (rng.standard_normal(n_samples_disc),
                           rng.standard_normal(n_samples_test))
        pos += sz
    labels[pos:] = "0"

    import zlib

    def build(names, n_samples, which):
        x = rng.standard_normal((n_samples, len(names)))
        for j, nm in enumerate(names):
            if nm in names_disc[: sum(module_sizes)]:
                k = labels[names_disc.index(nm)]
                if k != "0":
                    # per-node sign and noise level are deterministic in the
                    # node name, hence consistent across datasets — gives the
                    # module a heterogeneous, *preserved* degree structure
                    # (cor.degree has no signal in equal-SNR toy data).
                    sgn = 1.0 if zlib.crc32(nm.encode()) % 3 else -1.0
                    lvl = 0.35 + 1.3 * ((zlib.crc32(nm.encode()[::-1]) % 97) / 97)
                    x[:, j] = sgn * latents[k][which] + lvl * noise * x[:, j]
        corr = np.corrcoef(x, rowvar=False)
        net = np.abs(corr) ** 2  # soft-threshold adjacency, beta=2
        np.fill_diagonal(net, 1.0)
        return x, corr, net

    d_data, d_corr, d_net = build(names_disc, n_samples_disc, 0)
    t_data, t_corr, t_net = build(names_test, n_samples_test, 1)

    return dict(
        discovery=dict(data=d_data, correlation=d_corr, network=d_net, names=names_disc),
        test=dict(data=t_data, correlation=t_corr, network=t_net, names=names_test),
        labels={nm: str(l) for nm, l in zip(names_disc, labels)},
        module_sizes=dict(zip((str(k) for k in range(1, len(module_sizes) + 1)), module_sizes)),
    )


@pytest.fixture
def toy_pair(rng):
    return make_toy_pair(rng)


@pytest.fixture(scope="module")
def toy_pair_module():
    return make_toy_pair(np.random.default_rng(42))
