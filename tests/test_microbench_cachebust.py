"""No timed microbench rep may reuse identical inputs (VERDICT r4 item 2).

The axon tunnel short-circuits repeated identical executions: the 7/31
live window printed 3.7 TB/s row-gather on an 819 GB/s part because every
timed rep re-ran the same jitted fn on the same arrays (BASELINE.md
"microbench-timing caveat"). Three layers of defense, all pinned here:

1. the shared ``bench()`` helper REFUSES to time on an accelerator unless
   given >= reps+warmup distinct input variants;
2. with enough variants, the timed calls are pairwise distinct and
   disjoint from the warmup calls;
3. every ``bench(...)`` call site in ``benchmarks/`` threads ``variants=``
   (static AST sweep — a CPU-quiet site would otherwise only blow up
   mid-tunnel-window, the worst possible time).
"""

import ast
import glob
import os
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    from microbench_parts import bench

    return bench


def test_bench_refuses_missing_or_insufficient_variants_on_accelerator(
    monkeypatch,
):
    bench = _bench()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    with pytest.raises(RuntimeError, match="DISTINCT input variants"):
        bench(lambda x: x, 1, reps=3, warmup=2)
    with pytest.raises(RuntimeError, match="DISTINCT input variants"):
        # 4 variants < reps+warmup = 5: some timed rep would repeat
        bench(lambda x: x, reps=3, warmup=2,
              variants=[(1,), (2,), (3,), (4,)])
    with pytest.raises(RuntimeError, match="DISTINCT input variants"):
        # enough entries but identical objects: every timed call is still
        # the same execution (review r5 — count alone is not enforcement)
        dup = ([1.0],)
        bench(lambda x: x, reps=3, warmup=2, variants=[dup] * 5)
    with pytest.raises(RuntimeError, match="value-distinct"):
        # distinct objects but identical VALUES (ADVICE r5): .copy()
        # variants pass an id check while the tunnel still short-circuits
        # the repeated execution — the value digest must reject them
        import numpy as np

        base = np.arange(6, dtype=np.float32)
        bench(lambda x: x, reps=3, warmup=2,
              variants=[(base.copy(),) for _ in range(5)])


def test_bench_timed_calls_distinct_and_disjoint_from_warmup(monkeypatch):
    bench = _bench()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(jax, "block_until_ready", lambda x: x)
    seen = []
    variants = [(i,) for i in range(5)]
    bench(seen.append, reps=3, warmup=2, variants=variants)
    warm, timed = seen[:2], seen[2:]
    assert len(timed) == 3
    assert len(set(timed)) == len(timed), "timed reps repeated an input"
    assert not set(timed) & set(warm), "a timed rep repeated a warmup input"


def test_bench_still_permissive_on_cpu():
    # CI and local smoke runs have no tunnel to fool; plain reps are fine
    bench = _bench()
    assert jax.default_backend() == "cpu"
    t = bench(lambda x: x, 1, reps=2, warmup=1)
    assert t >= 0


def test_every_bench_call_site_threads_variants():
    sites = []
    for path in glob.glob(os.path.join(REPO, "benchmarks", "*.py")):
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "bench"
            ):
                if not any(kw.arg == "variants" for kw in node.keywords):
                    sites.append(f"{os.path.basename(path)}:{node.lineno}")
    assert not sites, (
        f"bench() call sites without variants= (tunnel-unsafe): {sites}"
    )


@pytest.mark.slow
def test_microbench_gather_smoke_cpu():
    # tiny-shape end-to-end run: every section must execute its variant
    # threading without error (run() converts failures to FAILED lines)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "NETREP_BACKEND_PROBE_TIMEOUT": "5",
    }
    proc = subprocess.run(
        [sys.executable, "benchmarks/microbench_gather.py",
         "--genes", "1200", "--modules", "3", "--chunk", "4", "--reps", "1"],
        cwd=REPO, env=env, timeout=600, capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "FAILED" not in proc.stdout, proc.stdout[-4000:]
