"""Pallas fused-statistics mega-kernel (ISSUE 8, ``stat_mode='fused'``) —
interpret-mode parity on CPU tier-1.

The parity contract (ops/fused_stats.py module docstring): within the mode,
streaming tallies equal ``tail_counts`` of the kernel's own materialized
null BIT-FOR-BIT (both outputs come from the same in-kernel registers —
the PR-2 carry contract); against the XLA composition, values agree at
float-rounding level (the re-batching drift class the autotune cache has
always documented) and counts / p-values / retirement decisions are pinned
EQUAL on these seeded fixtures. The mixed fixture spans multiple bucket
capacities with padded tails, and the chunk/superchunk sizes leave partial
tails so the validity-mask path runs in every assertion.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.ops import stats as jstats
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig

# chunk 64 / superchunk 3 / N_PERM 160: partial tail chunk AND partial tail
# superchunk — the masked-validity path runs in every parity assertion
N_PERM = 160


def _cfg(stat_mode="fused", **kw):
    base = dict(chunk_size=64, summary_method="power", power_iters=12,
                superchunk=3, autotune=False, stat_mode=stat_mode)
    base.update(kw)
    return EngineConfig(**base)


@pytest.fixture(scope="module")
def mixed():
    # module_size spread forces MULTIPLE bucket capacities (16/32/64) with
    # padded tails — the kernel compiles and runs once per cap
    return make_mixed_pair(400, 6, n_samples=40, module_size=(10, 40),
                           seed=1)


def _engine(mixed, config):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=config
    )


@pytest.fixture(scope="module")
def runs(mixed):
    """Fused materialized + fused streaming + XLA materialized, same key —
    shared by the parity assertions (each engine build compiles the
    interpret-mode kernel once)."""
    e_f = _engine(mixed, _cfg())
    assert e_f.stat_mode == "fused"
    assert len({b.cap for b in e_f.buckets}) >= 2  # multi-bucket coverage
    observed = np.asarray(e_f.observed())
    nulls_f, done_f = e_f.run_null(N_PERM, key=0)
    stream_f = e_f.run_null_streaming(N_PERM, observed, key=0)
    nulls_x, done_x = _engine(mixed, _cfg("xla")).run_null(N_PERM, key=0)
    return dict(observed=observed, nulls_f=np.asarray(nulls_f),
                done_f=done_f, stream=stream_f,
                nulls_x=np.asarray(nulls_x), done_x=done_x)


# ---------------------------------------------------------------------------
# the carry contract: streaming counts == the kernel's own materialized null
# ---------------------------------------------------------------------------

def test_stream_counts_equal_own_materialized(runs):
    """The robust bit contract: counts-mode tallies and values-mode
    statistics come from the same in-kernel registers."""
    sc = runs["stream"]
    assert sc.completed == runs["done_f"] == N_PERM
    hi, lo, eff = pv.tail_counts(runs["observed"],
                                 runs["nulls_f"][: runs["done_f"]])
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)


def test_values_match_xla_at_rounding_level(runs):
    """Cross-path values drift only at float-rounding level (~1e-7 — the
    lax.map re-batching class), and the fixture's counts are EQUAL."""
    drift = np.nanmax(np.abs(runs["nulls_f"] - runs["nulls_x"]))
    assert drift < 1e-5, drift
    hi, lo, eff = pv.tail_counts(runs["observed"],
                                 runs["nulls_x"][: runs["done_x"]])
    sc = runs["stream"]
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)


def test_pvalues_match_xla(runs):
    sc = runs["stream"]
    for alt in ("greater", "less", "two.sided"):
        want = pv.permutation_pvalues(
            runs["observed"], runs["nulls_x"][: runs["done_x"]], alt
        )
        got = pv.counts_pvalues(runs["observed"], sc.hi, sc.lo, sc.eff, alt)
        np.testing.assert_array_equal(want, got)


# ---------------------------------------------------------------------------
# all four null-loop modes
# ---------------------------------------------------------------------------

def test_adaptive_modes_match_xla(mixed, runs):
    """Adaptive materialized + adaptive streaming under stat_mode='fused'
    reach the XLA run's retirement decisions, n_perm_used, and counts."""
    observed = runs["observed"]
    na, da, fin = _engine(mixed, _cfg()).run_null_adaptive(
        480, observed, key=0
    )
    sca = _engine(mixed, _cfg()).run_null_adaptive_streaming(
        480, observed, key=0
    )
    scx = _engine(mixed, _cfg("xla")).run_null_adaptive_streaming(
        480, observed, key=0
    )
    assert sca.finished == fin
    nulls_a = np.asarray(na)[:da]
    np.testing.assert_array_equal(sca.n_perm_used, pv.effective_nperm(nulls_a))
    np.testing.assert_array_equal(sca.n_perm_used, scx.n_perm_used)
    hi, lo, eff = pv.tail_counts(observed, nulls_a)
    np.testing.assert_array_equal(sca.hi, hi)
    np.testing.assert_array_equal(sca.hi, scx.hi)
    np.testing.assert_array_equal(sca.lo, scx.lo)
    np.testing.assert_array_equal(sca.eff, scx.eff)


def test_exact_hilo_path(mixed, runs):
    """fused_exact='always' forces the hi/lo split select in interpret
    mode (CI coverage of the exact engine path); on CPU the split is a
    value-identical reformulation, so every count matches."""
    e = _engine(mixed, _cfg(fused_exact="always"))
    sc = e.run_null_streaming(N_PERM, runs["observed"], key=0)
    np.testing.assert_array_equal(sc.hi, runs["stream"].hi)
    np.testing.assert_array_equal(sc.lo, runs["stream"].lo)
    np.testing.assert_array_equal(sc.eff, runs["stream"].eff)


def test_checkpoint_resume_mid_run(mixed, runs, tmp_path):
    """Mid-run checkpoint resume with stat_mode='fused' reproduces the
    uninterrupted run exactly (satellite acceptance)."""
    seen = []

    def interrupt(done, total):
        seen.append(done)
        if len(seen) == 1:
            raise KeyboardInterrupt

    ck = str(tmp_path / "fused_stream.npz")
    # superchunk=1: progress fires per chunk, so the interrupt lands
    # mid-run (the fixture's superchunk 3 covers the whole run in one
    # dispatch); counts are superchunk-invariant, so the reference holds
    part = _engine(mixed, _cfg(superchunk=1)).run_null_streaming(
        N_PERM, runs["observed"], key=0, progress=interrupt,
        checkpoint_path=ck, checkpoint_every=64,
    )
    assert 0 < part.completed < N_PERM
    fin = _engine(mixed, _cfg(superchunk=1)).run_null_streaming(
        N_PERM, runs["observed"], key=0, checkpoint_path=ck,
        checkpoint_every=64,
    )
    assert fin.completed == N_PERM
    np.testing.assert_array_equal(fin.hi, runs["stream"].hi)
    np.testing.assert_array_equal(fin.lo, runs["stream"].lo)
    np.testing.assert_array_equal(fin.eff, runs["stream"].eff)


# ---------------------------------------------------------------------------
# mesh composition: perm-axis shard_map + the ring-exchange row-sharded path
# ---------------------------------------------------------------------------

def test_perm_mesh_parity(mixed, runs):
    from netrep_tpu.parallel import mesh as meshmod

    mesh = meshmod.make_mesh(n_perm_shards=4)
    cfg = _cfg(chunk_size=32, superchunk=2)
    eng = _engine_mesh(mixed, cfg, mesh)
    nulls, done = eng.run_null(80, key=0)
    sc = eng.run_null_streaming(80, runs["observed"], key=0)
    hi, lo, eff = pv.tail_counts(runs["observed"], np.asarray(nulls)[:done])
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)


def _engine_mesh(mixed, config, mesh, sharding=None):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    if sharding is not None:
        import dataclasses

        config = dataclasses.replace(config, matrix_sharding=sharding)
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=config,
        mesh=mesh,
    )


def test_ring_parity_row_sharded(mixed, runs):
    """The ring-exchange path (row-sharded matrices, chunk split over
    perm × row, neighbor collective-permute replacing the psum): counts
    equal both the ring's own materialized null and the XLA row-sharded
    streaming run."""
    from netrep_tpu.parallel import mesh as meshmod

    mesh = meshmod.make_mesh(n_perm_shards=2, n_row_shards=2)
    cfg = _cfg(chunk_size=32, superchunk=2)
    eng = _engine_mesh(mixed, cfg, mesh, sharding="row")
    assert eng._stat_fused_ring()
    # effective chunk rounds over BOTH axes (perm 2 × row 2)
    assert eng.effective_chunk() % 4 == 0
    nulls, done = eng.run_null(80, key=0)
    sc = eng.run_null_streaming(80, runs["observed"], key=0)
    hi, lo, eff = pv.tail_counts(runs["observed"], np.asarray(nulls)[:done])
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)
    scx = _engine_mesh(mixed, _cfg("xla", chunk_size=32, superchunk=2),
                       mesh, sharding="row").run_null_streaming(
        80, runs["observed"], key=0
    )
    np.testing.assert_array_equal(sc.hi, scx.hi)
    np.testing.assert_array_equal(sc.lo, scx.lo)
    np.testing.assert_array_equal(sc.eff, scx.eff)


# ---------------------------------------------------------------------------
# multi-test engine
# ---------------------------------------------------------------------------

def test_multitest_fused_parity():
    from netrep_tpu.parallel.multitest import MultiTestEngine

    mixed = make_mixed_pair(160, 3, n_samples=24, seed=5)
    (dd, dc, dn) = mixed["discovery"]
    (td, tc, tn) = mixed["test"]
    (td2, tc2, tn2) = make_mixed_pair(160, 3, n_samples=24, seed=6)["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]

    def make(stat_mode):
        cfg = _cfg(stat_mode, chunk_size=32, power_iters=10, superchunk=2)
        return MultiTestEngine(
            dc, dn, dd, np.stack([tc, tc2]), np.stack([tn, tn2]),
            [td, td2], specs, mixed["pool"], config=cfg,
        )

    eng = make("fused")
    assert eng.stat_mode == "fused"
    obs = np.asarray(eng.observed())
    nulls, done = eng.run_null(80, key=0)
    pf = np.asarray(nulls)[:, :done].transpose(1, 0, 2, 3)
    hi, lo, eff = pv.tail_counts(obs, pf)
    sc = make("fused").run_null_streaming(80, obs, key=0)
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)
    scx = make("xla").run_null_streaming(80, obs, key=0)
    np.testing.assert_array_equal(sc.hi, scx.hi)
    np.testing.assert_array_equal(sc.lo, scx.lo)
    np.testing.assert_array_equal(sc.eff, scx.eff)


# ---------------------------------------------------------------------------
# kernel units / configuration surface
# ---------------------------------------------------------------------------

def test_kernel_counts_are_its_own_values():
    """Unit-level form of the carry contract, including derived-net and
    the data-less NaN pattern."""
    from netrep_tpu.ops.fused_stats import (
        fused_stats_counts, fused_stats_values,
    )

    rng = np.random.default_rng(0)
    n, s, cap, K, B = 96, 16, 24, 2, 6
    x = rng.standard_normal((s, n)).astype(np.float32)
    tc = np.corrcoef(x, rowvar=False).astype(np.float32)
    np.fill_diagonal(tc, 1.0)
    tc_j = jnp.asarray(tc)
    tdT = jnp.asarray(x.T)
    mask = np.zeros((K, cap), np.float32)
    didx = np.zeros((K, cap), np.int32)
    for k, sz in enumerate((24, 17)):  # one padded-tail module
        mask[k, :sz] = 1
        didx[k, :sz] = rng.choice(n, sz, replace=False)
    sub = jax.vmap(lambda ix: tc_j[ix[:, None], ix[None, :]])(
        jnp.asarray(didx)
    )
    disc = jstats.make_disc_props(
        sub, jstats.derived_net(sub, 2.0),
        jax.vmap(lambda ix: jnp.take(jnp.asarray(x), ix, axis=1))(
            jnp.asarray(didx)
        ),
        jnp.asarray(mask),
    )
    idx = rng.integers(0, n, size=(B, K, cap)).astype(np.int32)
    obs = jnp.asarray(
        rng.standard_normal((K, 7)).astype(np.float32) * 0.05
    )
    pvalid = jnp.asarray(np.array([1] * (B - 2) + [0] * 2, np.int32))
    vals, hi, lo, eff = jax.jit(
        lambda ix: fused_stats_counts(
            tc_j, None, tdT, disc, ix, pvalid, obs, net_beta=2.0,
            n_iter=10, interpret=True,
        )
    )(jnp.asarray(idx))
    vals = np.asarray(vals)
    sel = np.asarray(pvalid)[:, None, None] > 0
    np.testing.assert_array_equal(
        np.asarray(hi), ((vals >= np.asarray(obs)[None]) & sel).sum(0)
    )
    np.testing.assert_array_equal(
        np.asarray(lo), ((vals <= np.asarray(obs)[None]) & sel).sum(0)
    )
    np.testing.assert_array_equal(
        np.asarray(eff), ((~np.isnan(vals)) & sel).sum(0)
    )
    # same registers in values mode
    v2 = np.asarray(jax.jit(
        lambda ix: fused_stats_values(
            tc_j, None, tdT, disc, ix, net_beta=2.0, n_iter=10,
            interpret=True,
        )
    )(jnp.asarray(idx)))
    np.testing.assert_array_equal(v2, vals)
    # data-less variant: the four data statistics are NaN, the topology
    # three finite (SURVEY.md §2.2)
    v3 = np.asarray(jax.jit(
        lambda ix: fused_stats_values(
            tc_j, None, None, disc, ix, net_beta=2.0, n_iter=10,
            interpret=True,
        )
    )(jnp.asarray(idx)))
    assert np.isnan(v3[..., [1, 4, 5, 6]]).all()
    assert np.isfinite(v3[..., [0, 2, 3]]).all()


def test_config_surface():
    from netrep_tpu.utils.autotune import resolve_fused_rowblock

    with pytest.raises(ValueError, match="stat_mode"):
        EngineConfig(stat_mode="mosaic")
    with pytest.raises(ValueError, match="power iteration"):
        EngineConfig(stat_mode="fused", summary_method="eigh")
    assert EngineConfig().resolved_stat_mode("cpu") == "xla"
    assert EngineConfig().resolved_stat_mode("tpu") == "fused"
    assert EngineConfig().resolved_stat_mode("axon") == "fused"
    assert EngineConfig(
        summary_method="eigh"
    ).resolved_stat_mode("tpu") == "xla"
    assert EngineConfig(stat_mode="xla").resolved_stat_mode("tpu") == "xla"
    # autotune=False → no lookup, no cache handle
    rb, cache = resolve_fused_rowblock(EngineConfig(autotune=False), "k")
    assert rb is None and cache is None


def test_row_block_budget_guard():
    from netrep_tpu.ops.fused_stats import resolve_row_block

    rb = resolve_row_block(128, 20_000, 4, s_pad=128, has_net=False,
                           has_data=True)
    assert rb % 8 == 0 and 8 <= rb <= 128
    # override honored after alignment + clamp
    assert resolve_row_block(128, 1000, 4, override=24) == 24
    assert resolve_row_block(128, 1000, 4, override=9) == 8
    with pytest.raises(ValueError, match="stat_mode='xla'"):
        resolve_row_block(128, 3_000_000, 4)


def test_multitest_row_sharded_refuses_explicit_fused():
    from netrep_tpu.parallel import mesh as meshmod
    from netrep_tpu.parallel.multitest import MultiTestEngine

    mixed = make_mixed_pair(160, 3, n_samples=24, seed=5)
    (dd, dc, dn) = mixed["discovery"]
    (td, tc, tn) = mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    mesh = meshmod.make_mesh(n_perm_shards=2, n_row_shards=2)
    cfg = _cfg(chunk_size=32, matrix_sharding="row")
    with pytest.raises(ValueError, match="multi-test"):
        MultiTestEngine(
            dc, dn, dd, np.stack([tc]), np.stack([tn]), [td], specs,
            mixed["pool"], config=cfg, mesh=mesh,
        )


def test_packed_engine_pinned_to_xla(toy_pair_module):
    """The serve pack engine draws one pool shuffle per key GROUP; the
    mega-kernel's single-group counter would break that RNG contract —
    the packed engine pins itself to the XLA composition."""
    from netrep_tpu.data import pair_frames
    from netrep_tpu.serve.packer import PackedEngine

    d, t = pair_frames(toy_pair_module)
    labels = dict(toy_pair_module["labels"])
    names = list(d["network"].columns)
    by_label = {}
    for nm, lab in labels.items():
        by_label.setdefault(lab, []).append(names.index(nm))
    specs = [
        ModuleSpec(str(lab), np.asarray(ix, np.int32),
                   np.asarray(ix, np.int32))
        for lab, ix in sorted(by_label.items())
    ]
    eng = PackedEngine(
        d["correlation"].to_numpy(), d["network"].to_numpy(),
        d["data"].to_numpy(), t["correlation"].to_numpy(),
        t["network"].to_numpy(), t["data"].to_numpy(),
        [specs], np.arange(len(names), dtype=np.int32),
        config=_cfg(),
    )
    assert eng.stat_mode == "xla"
