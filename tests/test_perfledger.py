"""Perf-regression ledger (ISSUE 5): entry golden shape, append/read
tolerance, the regression check (including the acceptance contract: the
ingested BENCH_r01–r05 history passes, a synthetically degraded entry
fails with a non-zero CLI exit), and the env-gated engine-loop feed.
Backend-free throughout — the ledger must work on a box whose tunnel is
dead."""

import json
import os

import pytest

from netrep_tpu.__main__ import main as cli_main
from netrep_tpu.utils import perfledger as pl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_FILES = [os.path.join(REPO, f"BENCH_r0{i}.json") for i in range(1, 6)]


def _entry(pps, fp="cpu|direct|caps:16x3|chunk:32", **kw):
    kw.setdefault("t", 0.0)
    return pl.make_entry(fp, pps, "run", backend="cpu", mode="materialized",
                         **kw)


# ---------------------------------------------------------------------------
# entry shape + IO
# ---------------------------------------------------------------------------

def test_entry_golden_shape():
    """Pinned key order + version of a ledger line — the parse surface of
    summarize_watch.py and any downstream dashboard."""
    e = pl.make_entry("fp", 123.4567899, "bench", backend="cpu",
                      mode="bench", compile_s=1.23456, n_perm=100,
                      run_id="r", round_n=3, metric="m", t=7.0)
    assert list(e) == ["perf_v", "t", "source", "round", "run",
                       "fingerprint", "backend", "mode", "perms_per_sec",
                       "compile_s", "n_perm", "metric"]
    assert e["perf_v"] == pl.ENTRY_VERSION == 1
    assert e["perms_per_sec"] == 123.4568 and e["compile_s"] == 1.2346


def test_append_read_skips_foreign_lines(tmp_path):
    path = str(tmp_path / "led.jsonl")
    with open(path, "w") as f:
        f.write("# comment\n")
        f.write(json.dumps({"metric": "bench row", "value": 1}) + "\n")
        f.write(json.dumps({"v": 1, "ev": "chunk", "data": {}}) + "\n")
        f.write("{broken json\n")
    assert pl.append_entry(_entry(10.0), path)
    rows = pl.read_entries(path)
    assert len(rows) == 1 and rows[0]["perms_per_sec"] == 10.0


def test_append_unwritable_warns_not_raises(tmp_path):
    # a FILE in the directory position makes the path unwritable even for
    # root (the suite runs as root, so permission bits alone don't block)
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    assert pl.append_entry(_entry(1.0), str(blocker / "led.jsonl")) is False


# ---------------------------------------------------------------------------
# regression check
# ---------------------------------------------------------------------------

def test_check_empty_and_baseline(tmp_path):
    path = str(tmp_path / "led.jsonl")
    open(path, "w").close()
    ok, rep = pl.check(path)
    assert ok and "no entries" in rep
    pl.append_entry(_entry(100.0), path)
    ok, rep = pl.check(path)
    assert ok and "baseline" in rep


def test_check_flags_regression_and_respects_fingerprint(tmp_path):
    path = str(tmp_path / "led.jsonl")
    for v in (100.0, 110.0, 90.0, 105.0):
        pl.append_entry(_entry(v), path)
    ok, rep = pl.check(path)
    assert ok
    # a different fingerprint's slow entry is a new baseline, NOT judged
    # against the fast history (a CPU-fallback row never compares to TPU)
    pl.append_entry(_entry(5.0, fp="tpu|other"), path)
    ok, rep = pl.check(path)
    assert ok and "baseline" in rep
    # same fingerprint, 2x regression -> fail
    pl.append_entry(_entry(40.0), path)
    ok, rep = pl.check(path)
    assert not ok and "PERF REGRESSION" in rep
    # threshold is honored
    ok, _ = pl.check(path, threshold=0.7)
    assert ok


def test_trend_renders_all_fingerprints(tmp_path):
    path = str(tmp_path / "led.jsonl")
    pl.append_entry(_entry(100.0), path)
    pl.append_entry(_entry(5.0, fp="tpu|other"), path)
    out = pl.trend(path)
    assert "2 entries, 2 fingerprint(s)" in out
    assert "tpu|other" in out


# ---------------------------------------------------------------------------
# engine-loop feed (env-gated)
# ---------------------------------------------------------------------------

def test_maybe_record_run_env_gated(tmp_path, monkeypatch):
    monkeypatch.delenv(pl.LEDGER_ENV, raising=False)
    assert not pl.maybe_record_run("fp", 10.0, "materialized", "cpu")
    path = str(tmp_path / "led.jsonl")
    monkeypatch.setenv(pl.LEDGER_ENV, path)
    assert pl.maybe_record_run("fp", 10.0, "materialized", "cpu",
                               compile_s=0.5, n_perm=64, run_id="r1")
    (row,) = pl.read_entries(path)
    assert row["source"] == "run" and row["run"] == "r1"
    # zero/negative throughput is never recorded
    assert not pl.maybe_record_run("fp", 0.0, "materialized", "cpu")


def test_bench_row_fingerprint_splits_backend_class():
    tpu = pl.bench_fingerprint({
        "metric": "wall-clock for 10000-perm null (north-star)",
        "device": "TPU_0(process=0)", "chunk": 256, "dtype": "float32"})
    cpu = pl.bench_fingerprint({
        "metric": "wall-clock for 10000-perm null [CPU fallback: dead]",
        "device": "TFRT_CPU_0", "chunk": 256, "dtype": "float32"})
    assert tpu != cpu
    # the config-note/fallback suffix is stripped: same base metric
    assert tpu.split("|")[1] == cpu.split("|")[1]
    assert pl.entry_from_bench_row({"metric": "x", "warning": "w"}) is None


# ---------------------------------------------------------------------------
# BENCH_r0* ingestion + CLI (the acceptance contract)
# ---------------------------------------------------------------------------

def test_ingest_bench_history_then_check_passes(tmp_path, capsys):
    """`perf --check` passes on the ingested BENCH_r01–r05 trajectory and
    exits 2 on a synthetically degraded entry — five PRs of history become
    a CI gate."""
    path = str(tmp_path / "led.jsonl")
    n = pl.ingest_bench_files(BENCH_FILES, path)
    assert n >= 4  # r01 TPU row + the CPU-fallback rows of r02..r05
    rows = pl.read_entries(path)
    assert all(r["source"] == "ingest" for r in rows)
    assert [r["round"] for r in rows] == sorted(r["round"] for r in rows)
    # distinct histories: the r01 TPU row must not share a fingerprint
    # with the CPU-fallback rows
    assert len({r["fingerprint"] for r in rows}) >= 2
    assert cli_main(["perf", path, "--check"]) == 0
    # synthetically degraded entry: 10x below the CPU history's median
    med = sorted(float(r["perms_per_sec"]) for r in rows
                 if r["backend"] == "cpu")[0]
    pl.append_entry(_entry(med / 10.0,
                           fp=[r for r in rows
                               if r["backend"] == "cpu"][-1]["fingerprint"]),
                    path)
    assert cli_main(["perf", path, "--check"]) == 2
    out = capsys.readouterr().out
    assert "PERF REGRESSION" in out


def test_cli_ingest_and_trend(tmp_path, capsys):
    path = str(tmp_path / "led.jsonl")
    assert cli_main(["perf", path, "--ingest", BENCH_FILES[0],
                     BENCH_FILES[4]]) == 0
    out = capsys.readouterr().out
    assert "ingested" in out
    assert cli_main(["perf", path]) == 0
    assert "fingerprint(s)" in capsys.readouterr().out


def test_cli_missing_ledger_errors(tmp_path, capsys):
    assert cli_main(["perf", str(tmp_path / "absent.jsonl")]) == 1
