"""network_properties / properties_table: observed per-module properties
and their tidy node-level export, pinned against the NumPy oracle."""

import numpy as np
import pandas as pd
import pytest

import netrep_tpu
from netrep_tpu.ops import oracle


@pytest.fixture(scope="module")
def toy():
    rng = np.random.default_rng(17)
    n, s = 60, 30
    x = rng.standard_normal((s, n)).astype(np.float32)
    z = (x - x.mean(0)) / x.std(0)
    c = np.clip(z.T @ z / s, -1, 1).astype(np.float32)
    np.fill_diagonal(c, 1.0)
    net = (np.abs(c) ** 2).astype(np.float32)
    labels = np.array(["1"] * 20 + ["2"] * 25 + ["0"] * 15)
    kw = dict(
        network={"d": net, "t": net}, data={"d": x, "t": x},
        correlation={"d": c, "t": c}, module_assignments=labels,
        discovery="d", test="t",
    )
    return x, net, labels, kw


def test_network_properties_shapes(toy):
    x, net, labels, kw = toy
    props = netrep_tpu.network_properties(**kw)
    assert set(props) == {"1", "2"}
    p1 = props["1"]
    assert len(p1["node_names"]) == 20
    assert p1["degree"].shape == (20,)
    assert p1["summary"].shape == (x.shape[0],)
    assert np.isfinite(p1["coherence"])


def test_properties_table_matches_oracle(toy):
    x, net, labels, kw = toy
    df = netrep_tpu.properties_table(**kw)
    assert isinstance(df, pd.DataFrame)
    assert list(df.columns) == ["discovery", "test", "module", "node",
                                "degree", "contribution", "avg_weight",
                                "coherence"]
    # one row per (module, node): modules 1 (20 nodes) and 2 (25 nodes)
    assert len(df) == 45
    assert set(df["module"]) == {"1", "2"}

    # pin module 1's rows against the oracle directly
    m1 = df[df["module"] == "1"].reset_index(drop=True)
    idx = np.arange(20)
    deg = oracle.weighted_degree(net[np.ix_(idx, idx)])
    deg = deg / np.max(np.abs(deg))
    np.testing.assert_allclose(m1["degree"].to_numpy(), deg, atol=1e-6)
    nc = oracle.node_contribution(x[:, idx])
    np.testing.assert_allclose(m1["contribution"].to_numpy(), nc, atol=1e-6)
    assert np.allclose(m1["avg_weight"].to_numpy(),
                       oracle.avg_edge_weight(net[np.ix_(idx, idx)]))
    assert np.allclose(m1["coherence"].to_numpy(), float(np.mean(nc ** 2)))


def test_properties_table_data_less(toy):
    _x, _net, _labels, kw = toy
    kw2 = {k: v for k, v in kw.items() if k != "data"}
    df = netrep_tpu.properties_table(**kw2)
    assert len(df) == 45
    assert df["contribution"].isna().all()
    assert df["coherence"].isna().all()
    assert np.isfinite(df["degree"].to_numpy()).all()
