"""Fault-tolerant null execution (ISSUE 4): error taxonomy, deterministic
fault-injection plans, retry/backoff, hung-dispatch abandonment, watchdog
warn→act escalation, mid-run CPU degradation, interrupt-resume via the
fault harness, and the bit-identical-when-disabled guarantee.

Everything runs on CPU with injected faults — fast, deterministic, tier-1.
The acceptance contract: for each of the four null-loop modes, a run with
injected transient failures (and a device-loss → CPU degradation run)
completes with results bit-identical to an unfaulted run at the same
seed, zero permutations lost, and the recovery sequence visible in the
telemetry JSONL.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils import checkpoint as ckpt
from netrep_tpu.utils.config import EngineConfig, FaultPolicy
from netrep_tpu.utils.faults import (
    DeviceLostError, DispatchAbandonedError, FaultRuntime, FaultSpec,
    InjectedDeviceLost, InjectedFatalError, InjectedTransientError,
    backoff_delay, classify_error, parse_plan, resolve_runtime,
)
from netrep_tpu.utils.telemetry import StallWatchdog, Telemetry, aggregate_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = EngineConfig(chunk_size=16, summary_method="eigh", superchunk=2,
                   autotune=False)
N_PERM = 64

MODES = ("fixed", "adaptive", "stream", "adaptive_stream")


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(120, 3, n_samples=16, seed=7)


@pytest.fixture(scope="module")
def eng(mixed):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=CFG
    )


@pytest.fixture(scope="module")
def observed(eng):
    return np.asarray(eng.observed())


def _run(eng, mode, observed, **kw):
    """One null run in the given loop mode; returns (kind, result,
    completed, finished) with kind 'mat' (null array) or 'sc'
    (StreamCounts)."""
    if mode == "fixed":
        nulls, done = eng.run_null(N_PERM, key=0, **kw)
        return "mat", nulls, done, done == N_PERM
    if mode == "adaptive":
        nulls, done, fin = eng.run_null_adaptive(
            N_PERM, observed, key=0, **kw
        )
        return "mat", nulls, done, fin
    if mode == "stream":
        sc = eng.run_null_streaming(N_PERM, observed, key=0, **kw)
        return "sc", sc, sc.completed, sc.completed == N_PERM
    sc = eng.run_null_adaptive_streaming(N_PERM, observed, key=0, **kw)
    return "sc", sc, sc.completed, sc.finished


def _assert_same(kind, a, b):
    if kind == "mat":
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    else:
        assert (a.hi == b.hi).all() and (a.lo == b.lo).all()
        assert (a.eff == b.eff).all()
        if a.n_perm_used is not None:
            np.testing.assert_array_equal(a.n_perm_used, b.n_perm_used)


@pytest.fixture(scope="module")
def baselines(eng, observed):
    """Unfaulted reference result per loop mode (the parity oracle)."""
    return {m: _run(eng, m, observed) for m in MODES}


# ---------------------------------------------------------------------------
# taxonomy / plans / backoff (pure units)
# ---------------------------------------------------------------------------

def test_classify_error():
    assert classify_error(InjectedTransientError("x")) == "transient"
    assert classify_error(DispatchAbandonedError("x")) == "transient"
    assert classify_error(InjectedDeviceLost("x")) == "device_lost"
    assert classify_error(InjectedFatalError("x")) == "fatal"
    assert classify_error(ConnectionResetError("peer")) == "transient"
    assert classify_error(TimeoutError("t")) == "transient"
    # message-based classification of generic backend errors
    assert classify_error(RuntimeError("DEADLINE_EXCEEDED: rpc")) == "transient"
    assert classify_error(RuntimeError("UNAVAILABLE: socket closed")) == "transient"
    assert classify_error(RuntimeError("device lost: chip 3")) == "device_lost"
    assert classify_error(RuntimeError("TPU worker preempted")) == "device_lost"
    # genuine bugs are never retried
    assert classify_error(ValueError("shapes differ")) == "fatal"
    assert classify_error(ZeroDivisionError()) == "fatal"


def test_parse_plan():
    plan = parse_plan("transient@8; device_lost@32x2,hang@64")
    assert plan == (
        FaultSpec("transient", 8), FaultSpec("device_lost", 32, 2),
        FaultSpec("hang", 64),
    )
    assert parse_plan(None) == () and parse_plan("") == ()
    assert parse_plan(plan) == plan  # FaultSpec tuples pass through
    with pytest.raises(ValueError, match="unknown fault kind"):
        parse_plan("flaky@3")
    with pytest.raises(ValueError, match="malformed"):
        parse_plan("transient")


def test_injector_consumes_times():
    from netrep_tpu.utils.faults import FaultInjector

    inj = FaultInjector(parse_plan("transient@8x2"))
    assert inj.poll(0, 16).kind == "transient"
    assert inj.poll(0, 16).kind == "transient"
    assert inj.poll(0, 16) is None          # consumed
    assert inj.poll(16, 16) is None         # out of range
    assert inj.pending == 0


def test_backoff_deterministic_and_bounded():
    pol = FaultPolicy(backoff_base_s=0.5, backoff_factor=2.0,
                      backoff_max_s=3.0, backoff_jitter=0.25)
    d1 = backoff_delay(pol, 128, 1)
    assert d1 == backoff_delay(pol, 128, 1)       # deterministic
    assert d1 != backoff_delay(pol, 128, 2)       # varies by attempt
    assert d1 != backoff_delay(pol, 256, 1)       # varies by chunk
    for attempt in range(1, 8):
        d = backoff_delay(pol, 0, attempt)
        assert 0.0 <= d <= 3.0 * 1.25             # capped (+jitter)
    # no jitter: the pure exponential schedule
    flat = FaultPolicy(backoff_base_s=1.0, backoff_jitter=0.0,
                       backoff_max_s=8.0)
    assert [backoff_delay(flat, 0, a) for a in (1, 2, 3, 4, 5)] == \
        [1.0, 2.0, 4.0, 8.0, 8.0]


def test_policy_validation():
    with pytest.raises(ValueError, match="max_retries"):
        FaultPolicy(max_retries=-1)
    with pytest.raises(ValueError, match="backoff_factor"):
        FaultPolicy(backoff_factor=0.5)
    with pytest.raises(ValueError, match="hang_timeout_s"):
        FaultPolicy(hang_timeout_s=0.0)
    with pytest.raises(ValueError, match="'hang' fault plan"):
        FaultRuntime(FaultPolicy(plan="hang@0"))  # needs hang_timeout_s
    with pytest.raises(TypeError, match="fault_policy"):
        resolve_runtime(object())


# ---------------------------------------------------------------------------
# dispatch wrapper (no engine: plain callables)
# ---------------------------------------------------------------------------

def _runtime(**kw):
    kw.setdefault("backoff_base_s", 0.0)
    kw.setdefault("backoff_jitter", 0.0)
    return FaultRuntime(FaultPolicy(**kw))


def test_run_dispatch_retries_then_succeeds():
    ft = _runtime(max_retries=3)
    tel = Telemetry(run_id="rt")
    calls = []

    def call():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionResetError("flaky")
        return "ok"

    assert ft.run_dispatch(call, start=0, take=16, telemetry=tel) == "ok"
    assert len(calls) == 3
    assert tel.metrics.counters["retry_attempt.count"] == 2


def test_run_dispatch_exhausted_retries_escalate_to_degradation():
    """A backend that fails every re-dispatch is as dead as a lost
    device: exhausted transient retries hand the run to the degradation
    ladder (reason='retries_exhausted') instead of crashing with the
    last transient error — unless degradation is disabled."""
    ft = _runtime(max_retries=2)
    calls = []

    def call():
        calls.append(1)
        raise ConnectionResetError("always")

    with pytest.raises(DeviceLostError) as ei:
        ft.run_dispatch(call, start=0, take=16)
    assert ei.value.reason == "retries_exhausted"
    assert len(calls) == 3  # initial + 2 retries
    ft2 = _runtime(max_retries=2, degrade_to_cpu=False)
    with pytest.raises(ConnectionResetError):
        ft2.run_dispatch(call, start=0, take=16)


def test_run_dispatch_fatal_not_retried():
    ft = _runtime(max_retries=5)
    calls = []

    def call():
        calls.append(1)
        raise ValueError("bug")

    with pytest.raises(ValueError):
        ft.run_dispatch(call, start=0, take=16)
    assert len(calls) == 1


def test_run_dispatch_device_lost_wraps_or_propagates():
    ft = _runtime()
    with pytest.raises(DeviceLostError):
        ft.run_dispatch(lambda: (_ for _ in ()).throw(
            InjectedDeviceLost("gone")), start=0, take=16)
    # degradation disabled: the original error surfaces
    ft2 = _runtime(degrade_to_cpu=False)
    with pytest.raises(InjectedDeviceLost):
        ft2.run_dispatch(lambda: (_ for _ in ()).throw(
            InjectedDeviceLost("gone")), start=0, take=16)


def test_run_dispatch_hang_abandons_and_redispatches():
    ft = _runtime(plan="hang@0", hang_timeout_s=0.05)
    tel = Telemetry(run_id="hang")
    rescued = []
    out = ft.run_dispatch(lambda: "real", start=0, take=16, telemetry=tel,
                          rescue=lambda: rescued.append(1))
    assert out == "real"
    assert rescued == [1]  # completed work checkpointed before re-dispatch
    assert tel.metrics.counters["chunk_abandoned.count"] == 1
    assert tel.metrics.counters["fault_injected.count"] == 1


def test_repeated_abandons_escalate_to_device_loss():
    ft = _runtime(plan="hang@0x5", hang_timeout_s=0.05, max_abandons=1)
    with pytest.raises(DeviceLostError, match="presumed dead") as ei:
        ft.run_dispatch(lambda: "x", start=0, take=16)
    assert ei.value.reason == "abandons_exhausted"


def test_watchdog_escalation_fires_action_once_per_episode():
    class Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = Clock()
    tel = Telemetry(clock=clock)
    acted = []
    wd = StallWatchdog(tel, factor=5.0, poll_interval=0, clock=clock,
                       action=lambda: acted.append(1), action_factor=20.0)
    wd.arm()
    wd.beat()
    for _ in range(3):
        clock.t += 1.0
        wd.beat()                   # steady state: 1 s / chunk
    clock.t += 10.0                 # > 5x steady: warn, < 20x: no action
    assert wd.poll() and acted == []
    clock.t += 15.0                 # now > 20x steady: act
    assert not wd.poll()            # same episode: no new stall event
    assert acted == [1]
    assert wd.poll() is False and acted == [1]  # once per episode
    clock.t += 1.0
    wd.beat()                       # recovery re-arms the action
    assert tel.metrics.counters["stall_recovered.count"] == 1
    clock.t += 50.0
    assert wd.poll() and acted == [1, 1]


# ---------------------------------------------------------------------------
# acceptance: four loop modes × injected transient faults → bit-identical,
# zero permutations lost, recovery sequence in the JSONL
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", MODES)
def test_transient_faults_bit_identical(eng, observed, baselines, mode,
                                        tmp_path):
    kind, base, base_done, _ = baselines[mode]
    pol = FaultPolicy(plan="transient@8;transient@40x2",
                      backoff_base_s=0.0, backoff_jitter=0.0)
    path = tmp_path / f"{mode}.jsonl"
    tel = Telemetry(path, run_id=mode)
    kind_f, res, done, finished = _run(
        eng, mode, observed, telemetry=tel, fault_policy=pol
    )
    tel.close()
    assert finished and done == base_done  # zero permutations lost
    _assert_same(kind, base, res)
    reg = aggregate_file(str(path))
    assert reg.counters["fault_injected.count"] == 3
    assert reg.counters["retry_attempt.count"] == 3
    # the recovery sequence is readable off the JSONL in order
    evs = [e["ev"] for e in map(json.loads, open(path))]
    assert evs.index("fault_injected") < evs.index("retry_attempt")


@pytest.mark.parametrize("mode", MODES)
def test_policy_on_unfaulted_bit_identical(eng, observed, baselines, mode):
    """The disabled⇒bit-identical guarantee, both ways: fault_policy=None
    IS the baseline path, and an armed-but-unfaulted policy must not
    perturb results either (same guarantee style as adaptive=False)."""
    kind, base, base_done, _ = baselines[mode]
    kind_f, res, done, _ = _run(
        eng, mode, observed,
        fault_policy=FaultPolicy(backoff_base_s=0.0),
    )
    assert done == base_done
    _assert_same(kind, base, res)


def test_hang_abandon_in_real_null_loop(eng, observed, baselines, tmp_path):
    """A hung chunk dispatch mid-run is abandoned and re-dispatched; the
    completed null is bit-identical and the emergency checkpoint fired."""
    kind, base, base_done, _ = baselines["fixed"]
    # the budget must exceed a real dispatch's wall time (compute included)
    # or healthy chunks get "abandoned" too; only the injected hang waits
    # the full budget out
    pol = FaultPolicy(plan="hang@32", hang_timeout_s=3.0,
                      backoff_base_s=0.0, backoff_jitter=0.0)
    path = tmp_path / "hang.jsonl"
    tel = Telemetry(path, run_id="hang")
    ck = str(tmp_path / "hang_ck.npz")
    nulls, done = eng.run_null(
        N_PERM, key=0, telemetry=tel, fault_policy=pol,
        checkpoint_path=ck, checkpoint_every=16,
    )
    tel.close()
    assert done == N_PERM
    np.testing.assert_array_equal(np.asarray(base), np.asarray(nulls))
    reg = aggregate_file(str(path))
    assert reg.counters["chunk_abandoned.count"] == 1
    # pinned event keys (golden shapes of the new recovery events)
    by_ev = {}
    for e in map(json.loads, open(path)):
        by_ev.setdefault(e["ev"], e["data"])
    # ISSUE 5: recovery events fired inside a chunk dispatch now carry a
    # `parent` pointing at that chunk's span — additive, schema unchanged
    assert set(by_ev["fault_injected"]) == {
        "kind", "at_perm", "start", "take", "label", "parent"}
    assert set(by_ev["chunk_abandoned"]) == {
        "start", "take", "waited_s", "by", "abandons", "label", "parent"}
    assert set(by_ev["retry_attempt"]) == {
        "start", "take", "attempt", "max_retries", "delay_s", "error",
        "label", "parent"}
    assert by_ev["fault_injected"]["parent"] == by_ev["retry_attempt"]["parent"]


# ---------------------------------------------------------------------------
# interrupt mid-chunk via the harness: valid resumable checkpoint in all
# four modes, resumed run bit-identical to uninterrupted
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", MODES)
def test_interrupt_leaves_resumable_checkpoint(eng, observed, baselines,
                                               mode, tmp_path):
    kind, base, base_done, _ = baselines[mode]
    ck = str(tmp_path / f"int_{mode}.npz")
    pol = FaultPolicy(plan="interrupt@32", backoff_base_s=0.0)
    kind_p, part, done, finished = _run(
        eng, mode, observed, fault_policy=pol,
        checkpoint_path=ck, checkpoint_every=16,
    )
    assert not finished and 0 < done < base_done
    saved = ckpt.load_null_checkpoint(ck)
    assert saved is not None and 0 < saved["completed"] <= done
    # resume (no plan) must equal the uninterrupted run exactly
    kind_r, res, done_r, finished_r = _run(
        eng, mode, observed, fault_policy=FaultPolicy(backoff_base_s=0.0),
        checkpoint_path=ck, checkpoint_every=16,
    )
    assert finished_r and done_r == base_done
    _assert_same(kind, base, res)


# ---------------------------------------------------------------------------
# device loss → emergency checkpoint → CPU degradation → exact resume
# ---------------------------------------------------------------------------

def test_device_loss_checkpoints_pending_work(eng, tmp_path):
    """Engine level: the failure-save hook flushes the pending chunk and
    the committed prefix before DeviceLostError propagates — no computed
    permutation is lost."""
    ck = str(tmp_path / "loss.npz")
    with pytest.raises(DeviceLostError):
        eng.run_null(
            N_PERM, key=0, checkpoint_path=ck, checkpoint_every=N_PERM,
            fault_policy=FaultPolicy(plan="device_lost@32",
                                     backoff_base_s=0.0),
        )
    saved = ckpt.load_null_checkpoint(ck)
    # chunks [0,16) and [16,32) committed (the pending chunk was flushed);
    # the failing dispatch started at 32
    assert saved["completed"] == 32


def test_device_loss_stream_resume_bit_identical(eng, observed, baselines,
                                                 tmp_path):
    kind, base, *_ = baselines["stream"]
    ck = str(tmp_path / "loss_stream.npz")
    with pytest.raises(DeviceLostError):
        eng.run_null_streaming(
            N_PERM, observed, key=0, checkpoint_path=ck,
            checkpoint_every=16,
            fault_policy=FaultPolicy(plan="device_lost@48",
                                     backoff_base_s=0.0),
        )
    saved = ckpt.load_null_checkpoint(ck)
    assert 0 < saved["completed"] < N_PERM
    sc = eng.run_null_streaming(N_PERM, observed, key=0, checkpoint_path=ck)
    _assert_same("sc", base, sc)


def test_device_loss_degrades_to_cpu_via_module_preservation(
        toy_pair_module, tmp_path):
    """The full degradation ladder through the public API: injected device
    loss → failure-save → degraded_to_cpu → engine rebuild → resume →
    bit-identical result, recovery sequence in the JSONL, emergency
    checkpoint dir cleaned up."""
    pytest.importorskip("pandas")
    from netrep_tpu import module_preservation
    from netrep_tpu.data import pair_frames

    d, t = pair_frames(toy_pair_module)
    kw = dict(
        network={"d": d["network"], "t": t["network"]},
        correlation={"d": d["correlation"], "t": t["correlation"]},
        data={"d": d["data"], "t": t["data"]},
        module_assignments=dict(toy_pair_module["labels"]),
        discovery="d", test="t", n_perm=64, seed=0,
        config=EngineConfig(chunk_size=16),
    )
    base = module_preservation(**kw)
    path = str(tmp_path / "degrade.jsonl")
    res = module_preservation(
        **kw, telemetry=path,
        fault_policy=FaultPolicy(plan="transient@8;device_lost@32",
                                 backoff_base_s=0.0, backoff_jitter=0.0),
    )
    assert res.completed == 64
    np.testing.assert_array_equal(base.nulls, res.nulls)
    np.testing.assert_array_equal(base.p_values, res.p_values)
    reg = aggregate_file(path)
    for ev, n in (("fault_injected", 2), ("retry_attempt", 1),
                  ("device_lost", 1), ("degraded_to_cpu", 1),
                  ("checkpoint_resumed", 1)):
        assert reg.counters.get(f"{ev}.count", 0) == n, ev
    assert reg.counters["checkpoint_saved.count"] >= 1
    # recovery order is readable off the JSONL
    evs = [e["ev"] for e in map(json.loads, open(path))]
    assert evs.index("device_lost") < evs.index("degraded_to_cpu")
    assert evs.index("degraded_to_cpu") < evs.index("checkpoint_resumed")
    # the emergency checkpoint dir (no checkpoint_dir was passed) is gone
    ck_paths = [
        e["data"]["path"] for e in map(json.loads, open(path))
        if e["ev"] == "checkpoint_saved"
    ]
    assert ck_paths and not any(os.path.exists(p) for p in ck_paths)


def test_degraded_rebuild_fingerprint_stable_across_layouts(tmp_path, caplog):
    """ISSUE 6: the checkpoint fingerprint digests the original HOST
    inputs, so a row-sharded run whose devices ALL die mid-null resumes
    on the replicated CPU rebuild with NO fingerprint mismatch — the
    ``accept_degraded_fingerprint`` seam (PR 5) is no longer needed for
    layout-only changes. Gene count 122 is deliberately not divisible by
    the 4 row shards (the sharded engine pads to 124), exactly the case
    that used to mismatch."""
    pytest.importorskip("jax")
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest 8-device CPU mesh")
    from netrep_tpu import module_preservation
    from netrep_tpu.parallel import mesh as meshmod

    mixed = make_mixed_pair(122, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    kw = dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", n_perm=64, seed=0,
    )
    base = module_preservation(**kw, config=EngineConfig(chunk_size=16))
    path = str(tmp_path / "degfp.jsonl")
    res = module_preservation(
        **kw, telemetry=path,
        mesh=meshmod.make_mesh(n_perm_shards=2, n_row_shards=4),
        config=EngineConfig(chunk_size=16, matrix_sharding="row"),
        # a FULL (unattributed) device loss: zero survivors, so the
        # ladder goes straight to the final CPU rung
        fault_policy=FaultPolicy(plan="device_lost@32", backoff_base_s=0.0,
                                 backoff_jitter=0.0),
    )
    assert res.completed == 64
    np.testing.assert_array_equal(base.nulls, res.nulls)
    np.testing.assert_array_equal(base.p_values, res.p_values)
    evs = [e["ev"] for e in map(json.loads, open(path))]
    # the layout change no longer trips the fingerprint check at all
    assert evs.count("fingerprint_degraded_accept") == 0
    assert "accepting the resume" not in caplog.text
    assert evs.count("mesh_shrunk") == 0  # unattributed loss: CPU rung
    assert evs.index("degraded_to_cpu") < evs.index("checkpoint_resumed")
    # freed-inventory satellite: the degraded event names the devices freed
    deg = next(e for e in map(json.loads, open(path))
               if e["ev"] == "degraded_to_cpu")
    assert len(deg["data"]["freed"]) == 8


def test_fingerprint_mismatch_still_refuses_outside_degraded_scope(tmp_path):
    """The acceptance is scoped to the degraded rebuild only: a plain
    mismatch (no accept scope) still refuses to resume."""
    from netrep_tpu.utils.checkpoint import (
        accept_degraded_fingerprint, validate_identity,
    )

    ck = {"fingerprint": np.frombuffer(b"old", dtype=np.uint8),
          "key_data": np.zeros(2, np.uint32), "completed": 8}
    new_fp = np.frombuffer(b"new", dtype=np.uint8)
    with pytest.raises(ValueError, match="different problem"):
        validate_identity(ck, np.zeros(2, np.uint32), new_fp, "p")
    with accept_degraded_fingerprint("device_lost"):
        validate_identity(ck, np.zeros(2, np.uint32), new_fp, "p")
    # the scope has exited: refusal is back
    with pytest.raises(ValueError, match="different problem"):
        validate_identity(ck, np.zeros(2, np.uint32), new_fp, "p")


# ---------------------------------------------------------------------------
# env toggle + satellites
# ---------------------------------------------------------------------------

def test_env_plan_activates_injection(eng, baselines, monkeypatch, tmp_path):
    """NETREP_FAULT_PLAN alone (no fault_policy argument) injects and
    recovers — the bench/CI drill switch."""
    kind, base, *_ = baselines["fixed"]
    monkeypatch.setenv("NETREP_FAULT_PLAN", "transient@8")
    path = tmp_path / "env.jsonl"
    tel = Telemetry(path, run_id="env")
    nulls, done = eng.run_null(N_PERM, key=0, telemetry=tel)
    tel.close()
    assert done == N_PERM
    np.testing.assert_array_equal(np.asarray(base), np.asarray(nulls))
    reg = aggregate_file(str(path))
    assert reg.counters["fault_injected.count"] == 1
    assert reg.counters["retry_attempt.count"] == 1


def test_trim_tail_shards_narrowed_except(monkeypatch, caplog):
    """Satellite: unknown-sharding objects downgrade with ONE warning;
    genuine backend failures inside shard_shape now propagate."""
    import logging

    from netrep_tpu.parallel import engine as eng_mod

    class NoShardShape:
        pass

    class FakeOut:
        shape = (8, 3)
        ndim = 2
        is_fully_addressable = False
        sharding = NoShardShape()

    monkeypatch.setattr(eng_mod, "_UNKNOWN_SHARDING_SEEN", False)
    out = FakeOut()
    with caplog.at_level(logging.WARNING, logger="netrep_tpu"):
        assert eng_mod._trim_tail_shards(out, 4) is out
        assert eng_mod._trim_tail_shards(out, 4) is out
    warns = [r for r in caplog.records if "trim skipped" in r.getMessage()]
    assert len(warns) == 1  # once per process, not per chunk

    class DeadSharding:
        def shard_shape(self, shape):
            raise RuntimeError("backend connection dropped")

    class DeadOut(FakeOut):
        sharding = DeadSharding()

    with pytest.raises(RuntimeError, match="connection dropped"):
        eng_mod._trim_tail_shards(DeadOut(), 4)


def test_distributed_autodetect_failure_emits_event(monkeypatch):
    """Satellite: the auto-detect join failure leaves a machine-readable
    event (the "other hosts will hang" precondition) beside the warning."""
    import jax

    from netrep_tpu.parallel import distributed

    monkeypatch.setattr(distributed, "is_initialized", lambda: False)
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: (_ for _ in ()).throw(RuntimeError("no coordinator")),
    )
    tel = Telemetry(run_id="dist")
    with tel.activate():
        out = distributed.initialize()
    assert out["process_count"] >= 1
    assert tel.metrics.counters["distributed_autodetect_failed.count"] == 1


def test_cli_recovery_timeline(tmp_path):
    path = tmp_path / "rec.jsonl"
    tel = Telemetry(path, run_id="cli")
    tel.emit("chunk", done=16, total=64, take=16, s=0.1)
    tel.emit("fault_injected", kind="transient", at_perm=8, start=0,
             take=16, label="chunk")
    tel.emit("retry_attempt", start=0, take=16, attempt=1, max_retries=3,
             delay_s=0.0, error="InjectedTransientError", label="chunk")
    tel.emit("degraded_to_cpu", reason="device_lost")
    tel.close()
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "telemetry", str(path),
         "--recovery"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.strip().splitlines()
    # the chunk event is not a recovery event; the degraded_to_cpu rung
    # additionally fires its pinned anomaly detector (ISSUE 20), whose
    # verdict renders with the detector label
    assert len(lines) == 4
    assert "fault_injected" in lines[0]
    assert "retry_attempt" in lines[1]
    assert "degraded_to_cpu" in lines[2]
    assert "anomaly_detected" in lines[3]
    assert "[detector=degraded_to_cpu]" in lines[3]
    # summary table leads with the recovery section
    table = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "telemetry", str(path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert table.returncode == 0
    assert "recovery:" in table.stdout
    assert table.stdout.index("recovery:") < table.stdout.index("counters:")


# ---------------------------------------------------------------------------
# elastic mesh execution (ISSUE 6): shrink onto survivors, grow back when
# capacity returns, CPU only when nothing survives — all four loop modes,
# bit-identical to the unfaulted run
# ---------------------------------------------------------------------------

#: module_preservation flags per loop mode (mirrors MODES at engine level)
MP_MODES = {
    "fixed": {},
    "adaptive": {"adaptive": True},
    "stream": {"store_nulls": False},
    "adaptive_stream": {"adaptive": True, "store_nulls": False},
}


@pytest.fixture(scope="module")
def mp_kw(mixed):
    """module_preservation kwargs over the shared mixed pair (numpy inputs;
    no pandas dependency)."""
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    return dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", n_perm=N_PERM, seed=0,
        config=EngineConfig(chunk_size=16, superchunk=2, autotune=False),
    )


@pytest.fixture(scope="module")
def mp_baselines(mp_kw):
    from netrep_tpu import module_preservation

    return {m: module_preservation(**mp_kw, **flags)
            for m, flags in MP_MODES.items()}


def _assert_same_result(a, b):
    np.testing.assert_array_equal(np.asarray(a.p_values),
                                  np.asarray(b.p_values))
    if a.nulls is not None or b.nulls is not None:
        np.testing.assert_array_equal(np.asarray(a.nulls),
                                      np.asarray(b.nulls))
    if a.counts_hi is not None or b.counts_hi is not None:
        np.testing.assert_array_equal(a.counts_hi, b.counts_hi)
        np.testing.assert_array_equal(a.counts_lo, b.counts_lo)
        np.testing.assert_array_equal(a.counts_eff, b.counts_eff)
    if a.n_perm_used is not None:
        np.testing.assert_array_equal(a.n_perm_used, b.n_perm_used)


def _perm_mesh(n):
    from netrep_tpu.parallel import mesh as meshmod

    return meshmod.make_mesh(n_perm_shards=n, n_row_shards=1)


@pytest.mark.parametrize("mode", MODES)
def test_elastic_shrink_then_grow_bit_identical(mp_kw, mp_baselines, mode,
                                                tmp_path):
    """THE acceptance drill: injected partial device loss on a 4-device
    mesh re-buckets onto the 2-device survivor mesh, capacity restored
    grows it back at the next boundary, and the final counts/p-values
    are bit-identical to the uninterrupted (no-mesh) run — in every
    loop mode. CPU degradation must NOT fire: survivors existed."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the conftest multi-device CPU platform")
    from netrep_tpu import module_preservation

    path = str(tmp_path / f"elastic_{mode}.jsonl")
    # loss at 8 (the first dispatch), restore polled on the re-dispatched
    # range after the shrink — leaves at least one boundary in EVERY mode
    # (the streaming superchunk covers 32 perms per dispatch) for the
    # grow-back to act on
    res = module_preservation(
        **mp_kw, **MP_MODES[mode], mesh=_perm_mesh(4), telemetry=path,
        fault_policy=FaultPolicy(
            plan="device_lost_partial@8;capacity_restored@24",
            backoff_base_s=0.0, backoff_jitter=0.0,
        ),
    )
    _assert_same_result(mp_baselines[mode], res)
    evs = [e["ev"] for e in map(json.loads, open(path))]
    assert evs.count("mesh_shrunk") == 1
    assert evs.count("mesh_grown") == 1
    assert evs.count("degraded_to_cpu") == 0
    assert (evs.index("device_lost") < evs.index("mesh_shrunk")
            < evs.index("mesh_grown"))
    # the shrink event carries the freed + surviving device inventories
    shrunk = next(e["data"] for e in map(json.loads, open(path))
                  if e["ev"] == "mesh_shrunk")
    assert shrunk["n_freed"] == 2 and shrunk["n_surviving"] == 2
    assert len(shrunk["freed"]) == 2 and len(shrunk["surviving"]) == 2
    # async checkpointing was active (fault policy default) and drained
    assert "checkpoint_async_flush" in evs


def test_cpu_rung_only_when_no_survivors(mp_kw, mp_baselines, tmp_path):
    """Two partial losses in sequence: 2-device mesh → shrink to 1 →
    the second loss leaves zero survivors → ONLY then the CPU rung.
    Result stays bit-identical throughout the whole ladder."""
    import jax

    if len(jax.devices()) < 2:
        pytest.skip("needs the conftest multi-device CPU platform")
    from netrep_tpu import module_preservation

    path = str(tmp_path / "cpu_rung.jsonl")
    res = module_preservation(
        **mp_kw, mesh=_perm_mesh(2), telemetry=path,
        fault_policy=FaultPolicy(
            plan="device_lost_partial@16;device_lost_partial@40",
            backoff_base_s=0.0, backoff_jitter=0.0,
        ),
    )
    _assert_same_result(mp_baselines["fixed"], res)
    evs = [e["ev"] for e in map(json.loads, open(path))]
    assert evs.count("mesh_shrunk") == 1
    assert evs.count("degraded_to_cpu") == 1
    assert evs.index("mesh_shrunk") < evs.index("degraded_to_cpu")
    deg = next(e["data"] for e in map(json.loads, open(path))
               if e["ev"] == "degraded_to_cpu")
    assert len(deg["freed"]) == 1  # the last surviving device, now gone


def test_mesh_rebuild_budget_skips_to_cpu(mp_kw, mp_baselines, tmp_path):
    """max_mesh_rebuilds=0: survivors exist but the elastic budget is
    spent — the ladder takes the CPU rung directly (and still resumes
    bit-identically)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the conftest multi-device CPU platform")
    from netrep_tpu import module_preservation

    path = str(tmp_path / "budget.jsonl")
    res = module_preservation(
        **mp_kw, mesh=_perm_mesh(4), telemetry=path,
        fault_policy=FaultPolicy(
            plan="device_lost_partial@24", max_mesh_rebuilds=0,
            backoff_base_s=0.0, backoff_jitter=0.0,
        ),
    )
    _assert_same_result(mp_baselines["fixed"], res)
    evs = [e["ev"] for e in map(json.loads, open(path))]
    assert evs.count("mesh_shrunk") == 0
    assert evs.count("degraded_to_cpu") == 1


# ---------------------------------------------------------------------------
# checkpoint identity across mesh shapes (ISSUE 6 satellite): one problem,
# one fingerprint — N devices, N−1, 1, replicated or row-sharded
# ---------------------------------------------------------------------------

def _mesh_engine(mixed, n_dev):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    mesh = _perm_mesh(n_dev) if n_dev and n_dev > 1 else None
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=CFG, mesh=mesh
    )


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("resume_dev", [2, 1])
def test_checkpoint_resumes_across_mesh_shapes(mixed, observed, baselines,
                                               mode, resume_dev, tmp_path):
    """A checkpoint written mid-run on a 4-device mesh resumes
    bit-identically on a 2-device mesh and on a single device, in all
    four loop modes — no accept_degraded_fingerprint seam involved
    (the fingerprint digests host inputs, not device layouts)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the conftest multi-device CPU platform")
    kind, base, base_done, _ = baselines[mode]
    ck = str(tmp_path / f"mesh_{mode}_{resume_dev}.npz")
    writer_eng = _mesh_engine(mixed, 4)
    pol = FaultPolicy(plan="interrupt@32", backoff_base_s=0.0)
    _run(writer_eng, mode, observed, fault_policy=pol,
         checkpoint_path=ck, checkpoint_every=16)
    saved = ckpt.load_null_checkpoint(ck)
    assert saved is not None and 0 < saved["completed"] < N_PERM
    resume_eng = _mesh_engine(mixed, resume_dev)
    kind_r, res, done_r, finished_r = _run(
        resume_eng, mode, observed, checkpoint_path=ck,
        checkpoint_every=16,
    )
    assert finished_r and done_r == base_done
    _assert_same(kind, base, res)


def test_checkpoint_resumes_on_n_minus_one_devices(mixed, observed,
                                                   baselines, tmp_path):
    """The literal N−1 case (4 → 3 devices; chunk 16 rounds to an
    effective 15 on the 3-shard mesh, so the resumed chunk boundaries
    genuinely differ) — the fixed null is still bit-identical because
    per-permutation keys depend only on (key, index)."""
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs the conftest multi-device CPU platform")
    kind, base, base_done, _ = baselines["fixed"]
    ck = str(tmp_path / "mesh_nm1.npz")
    _run(_mesh_engine(mixed, 4), "fixed", observed,
         fault_policy=FaultPolicy(plan="interrupt@32", backoff_base_s=0.0),
         checkpoint_path=ck, checkpoint_every=16)
    kind_r, res, done_r, finished_r = _run(
        _mesh_engine(mixed, 3), "fixed", observed, checkpoint_path=ck,
    )
    assert finished_r and done_r == base_done
    _assert_same(kind, base, res)


# ---------------------------------------------------------------------------
# async checkpoint writer (ISSUE 6): background saves, latest-wins queue,
# flush durability, no completed permutation lost under interrupt
# ---------------------------------------------------------------------------

def test_async_writer_latest_wins_and_flush():
    import threading
    import time as _time

    from netrep_tpu.utils.checkpoint import AsyncCheckpointWriter
    from netrep_tpu.utils.telemetry import Telemetry

    tel = Telemetry(run_id="aw")
    w = AsyncCheckpointWriter(tel)
    wrote = []
    gate = threading.Event()

    def slow():
        gate.wait(5.0)
        wrote.append("slow")

    assert w.submit(slow)
    _time.sleep(0.05)          # let the worker pick `slow` up (now busy)
    assert w.submit(lambda: wrote.append("a"))
    assert w.submit(lambda: wrote.append("b"))   # supersedes "a"
    gate.set()
    w.flush()
    assert wrote == ["slow", "b"]                # latest wins, "a" dropped
    w.close()
    assert not w.submit(lambda: wrote.append("late"))  # closed → sync path
    assert tel.metrics.counters["checkpoint_async_flush.count"] == 1
    assert tel.metrics.gauges["checkpoint_async_flush.superseded"] == 1


def test_async_checkpoint_never_loses_completed_perms(eng, observed,
                                                      baselines, tmp_path):
    """Acceptance: with async checkpointing active, an injected interrupt
    mid-run still leaves every completed permutation on disk (the writer
    is flushed before the loop returns), and the resume is
    bit-identical."""
    kind, base, base_done, _ = baselines["fixed"]
    ck = str(tmp_path / "async_int.npz")
    path = tmp_path / "async_int.jsonl"
    tel = Telemetry(path, run_id="async")
    pol = FaultPolicy(plan="interrupt@40", backoff_base_s=0.0,
                      async_checkpoint=True)
    nulls, done = eng.run_null(
        N_PERM, key=0, telemetry=tel, fault_policy=pol,
        checkpoint_path=ck, checkpoint_every=16,
    )
    tel.close()
    saved = ckpt.load_null_checkpoint(ck)
    # zero loss: everything the loop committed is on disk
    assert saved["completed"] == done > 0
    reg = aggregate_file(str(path))
    assert reg.counters["checkpoint_async_flush.count"] >= 1
    res, done_r = eng.run_null(N_PERM, key=0, checkpoint_path=ck)
    assert done_r == base_done
    np.testing.assert_array_equal(np.asarray(base), np.asarray(res))


def test_async_checkpoint_off_stays_synchronous(eng, tmp_path):
    """async_checkpoint=False: no writer thread, no flush event — every
    save is the plain synchronous path."""
    path = tmp_path / "sync.jsonl"
    tel = Telemetry(path, run_id="sync")
    with tel.activate():  # checkpoint_saved rides the ambient bus
        nulls, done = eng.run_null(
            N_PERM, key=0, telemetry=tel,
            fault_policy=FaultPolicy(backoff_base_s=0.0,
                                     async_checkpoint=False),
            checkpoint_path=str(tmp_path / "sync.npz"), checkpoint_every=16,
        )
    tel.close()
    assert done == N_PERM
    reg = aggregate_file(str(path))
    assert "checkpoint_async_flush.count" not in reg.counters
    assert reg.counters["checkpoint_saved.count"] >= 1


# ---------------------------------------------------------------------------
# chaos-drill matrix (ISSUE 6 satellite): one NETREP_FAULT_PLAN per ladder
# rung, through the public API, CPU-only, tier-1
# ---------------------------------------------------------------------------

LADDER_PLANS = {
    "retry": ("transient@8", ("retry_attempt",), 1),
    "shrink": ("device_lost_partial@24", ("mesh_shrunk",), 4),
    "grow": ("device_lost_partial@24;capacity_restored@40",
             ("mesh_shrunk", "mesh_grown"), 4),
    "cpu": ("device_lost@24", ("degraded_to_cpu",), 4),
}


@pytest.mark.parametrize("rung", sorted(LADDER_PLANS))
def test_chaos_matrix_env_plan_per_rung(mp_kw, mp_baselines, rung,
                                        monkeypatch, tmp_path):
    """NETREP_FAULT_PLAN alone drills every ladder rung through
    module_preservation (the CI chaos matrix): the env var activates a
    default policy, the run recovers, and the result is bit-identical."""
    import jax

    plan, want_events, need_dev = LADDER_PLANS[rung]
    if len(jax.devices()) < need_dev:
        pytest.skip("needs the conftest multi-device CPU platform")
    from netrep_tpu import module_preservation

    monkeypatch.setenv("NETREP_FAULT_PLAN", plan)
    path = str(tmp_path / f"chaos_{rung}.jsonl")
    res = module_preservation(
        **mp_kw, telemetry=path,
        mesh=_perm_mesh(need_dev) if need_dev > 1 else None,
    )
    _assert_same_result(mp_baselines["fixed"], res)
    evs = [e["ev"] for e in map(json.loads, open(path))]
    for ev in want_events:
        assert ev in evs, (rung, ev, [e for e in evs if "mesh" in e])


def test_elastic_shrink_preserves_row_sharding(mp_baselines, tmp_path):
    """A row-sharded (2-perm × 4-row) mesh losing half its devices
    shrinks to a mesh that KEEPS the 4-way row sharding
    (shrink_mesh picks the largest still-dividing row factor) and
    resumes bit-identically — the large-n engine does not silently fall
    back to replicated matrices while survivors can still hold shards."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the conftest 8-device CPU mesh")
    from netrep_tpu import module_preservation
    from netrep_tpu.parallel import mesh as meshmod

    mixed = make_mixed_pair(120, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    path = str(tmp_path / "rowshrink.jsonl")
    res = module_preservation(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", n_perm=N_PERM, seed=0,
        config=EngineConfig(chunk_size=16, matrix_sharding="row",
                            autotune=False),
        mesh=meshmod.make_mesh(n_perm_shards=2, n_row_shards=4),
        telemetry=path,
        fault_policy=FaultPolicy(plan="device_lost_partial@24",
                                 backoff_base_s=0.0, backoff_jitter=0.0),
    )
    base = mp_baselines["fixed"]
    np.testing.assert_array_equal(np.asarray(base.p_values),
                                  np.asarray(res.p_values))
    np.testing.assert_array_equal(base.nulls, res.nulls)
    shrunk = next(e["data"] for e in map(json.loads, open(path))
                  if e["ev"] == "mesh_shrunk")
    assert shrunk["n_surviving"] == 4
    evs = [e["ev"] for e in map(json.loads, open(path))]
    assert "degraded_to_cpu" not in evs
    # no fingerprint escape hatch involved: padding changed (none here,
    # 120 % 4 == 0) but more importantly the digest is layout-free
    assert "fingerprint_degraded_accept" not in evs
