"""`profile=` flag on module_preservation (SURVEY.md §5 "Tracing/profiling":
the reference has only a progress bar + verbose messages; the rebuild exposes
jax.profiler traces + per-pair/per-chunk timings as a first-class flag)."""

import glob
import os

import numpy as np
import pytest

from netrep_tpu import module_preservation
from netrep_tpu.utils.config import EngineConfig
from netrep_tpu.utils.profiling import resolve_profile_dir, summarize_trace

try:
    import pandas as pd
except Exception:
    pd = None

pytestmark = pytest.mark.skipif(pd is None, reason="pandas required")

CFG = EngineConfig(chunk_size=32)


def _kwargs(pair, with_data=True):
    d, t = pair["discovery"], pair["test"]
    frame = lambda ds: pd.DataFrame(
        ds["network"], index=ds["names"], columns=ds["names"]
    )
    corr = lambda ds: pd.DataFrame(
        ds["correlation"], index=ds["names"], columns=ds["names"]
    )
    kw = dict(
        network={"d": frame(d), "t": frame(t)},
        correlation={"d": corr(d), "t": corr(t)},
        module_assignments=dict(pair["labels"]),
        discovery="d", test="t", seed=0, config=CFG,
    )
    if with_data:
        kw["data"] = {
            "d": pd.DataFrame(d["data"], columns=d["names"]),
            "t": pd.DataFrame(t["data"], columns=t["names"]),
        }
    return kw


def test_profile_attaches_timings_and_trace(toy_pair_module, tmp_path):
    trace_dir = str(tmp_path / "trace")
    res = module_preservation(
        **_kwargs(toy_pair_module), n_perm=64, profile=trace_dir
    )
    p = res.profile
    assert p is not None
    assert p["trace_dir"] == trace_dir
    assert p["observed_s"] > 0
    assert p["null_s"] > 0
    assert p["completed"] == 64
    assert p["perms_per_sec"] > 0
    assert len(p["chunk_ms"]) == 2  # 64 perms / chunk 32
    assert p["compile_chunk_ms"] == p["chunk_ms"][0]
    # the trace artifact (VERDICT.md item 2 "Done" criterion): jax.profiler
    # writes an .xplane.pb under the requested directory (device_trace is
    # best-effort on exotic backends; on the CPU CI platform it must exist)
    assert os.path.isdir(trace_dir)
    assert glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                     recursive=True), "no xplane trace written"
    # summarize_trace parses the artifact without raising; host-only traces
    # may have no device plane → empty list is acceptable
    summary = summarize_trace(trace_dir)
    assert isinstance(summary, list)


def test_profile_off_by_default(toy_pair_module):
    res = module_preservation(**_kwargs(toy_pair_module), n_perm=16)
    assert res.profile is None


def test_resolve_profile_dir():
    assert resolve_profile_dir(None) is None
    assert resolve_profile_dir(False) is None
    assert resolve_profile_dir(True).endswith("netrep_profile")
    assert resolve_profile_dir("/x/y") == "/x/y"


@pytest.mark.slow
def test_profile_dataless_run(toy_pair_module, tmp_path):
    # slow tier (ISSUE 15 wall-clock satellite): the dataless ENGINE path
    # is pinned by the engine/e2e suites and the profiling machinery by
    # test_profile_attaches_timings_and_trace — this full extra
    # module_preservation run only re-proves their composition
    res = module_preservation(
        **_kwargs(toy_pair_module, with_data=False),
        n_perm=32, profile=str(tmp_path / "t2"),
    )
    # data-less run: timings still collected
    assert res.profile["null_s"] > 0
    assert np.isfinite(res.profile["chunk_ms"]).all()


def test_pair_timer_finish_null_without_wrap_progress():
    """Zero-chunk / failed null path: wrap_progress never ran, so the
    null start mark is unset — finish_null must report unmeasured, not
    crash (ISSUE 3 satellite)."""
    from netrep_tpu.utils.profiling import PairTimer

    t = PairTimer(None)
    t.time_observed(lambda: 1)
    d = t.finish_null(0)
    assert d["null_s"] is None
    assert d["perms_per_sec"] is None
    assert d["completed"] == 0


def test_trace_time_split_classification(monkeypatch):
    """Op-name classification on a synthetic per-op duration table: the
    transfer patterns win over scan patterns, scan patterns over 'other',
    and the fractions come out of the bucket sums."""
    from netrep_tpu.utils import profiling

    monkeypatch.setattr(profiling, "_device_op_durations", lambda d: {
        "copy-start": 2e6,          # transfer (copy)
        "dynamic-slice": 1e6,       # other
        "while": 3e6,               # scan body (lax.scan lowers to while)
        "loop_body_fusion": 4e6,    # scan body ('body')
        "outfeed.1": 5e6,           # transfer
        "fusion": 6e6,              # other
    })
    split = profiling.trace_time_split("ignored")
    assert split["transfer_ms"] == pytest.approx(7.0)
    assert split["scan_body_ms"] == pytest.approx(7.0)
    assert split["other_ms"] == pytest.approx(7.0)
    assert split["total_ms"] == pytest.approx(21.0)
    assert split["transfer_frac"] == pytest.approx(7.0 / 21.0)


def test_trace_time_split_zero_total(monkeypatch):
    """Empty trace (host-only plane): all buckets zero and the fraction
    is defined as 0.0, not NaN/ZeroDivisionError."""
    from netrep_tpu.utils import profiling

    monkeypatch.setattr(profiling, "_device_op_durations", lambda d: {})
    split = profiling.trace_time_split("ignored")
    assert split == {
        "scan_body_ms": 0.0, "transfer_ms": 0.0, "other_ms": 0.0,
        "total_ms": 0.0, "transfer_frac": 0.0,
    }
