"""End-to-end request tracing + deterministic cost attribution + the
`top` ops surface (ISSUE 13) — CPU-only, in-process, tiny fixtures.

The conservation contract: every pack member's attributed
``device_s``/``transfer_s``/``perms``/``bytes_to_host``/
``compile_s_amortized`` sum BIT-EXACTLY (f64 host arithmetic, ``==`` not
approx) to the pack totals, in fixed-n, mixed-budget, adaptive, and
deadline-expiry compositions (the SIGKILL→``--recover`` composition is
pinned in tests/test_serve_recovery.py beside the parity drill). Trace
contexts: client-minted ids ride every request's span subtree and the
journal. Telemetry-off: no cost tracking, no new result keys — the PR 12
behavior bit-identical."""

import json

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.serve import (
    InProcessClient, PreservationServer, ServeConfig, ServeError,
)
from netrep_tpu.serve.packer import PackMonitor
from netrep_tpu.serve.protocol import mint_trace_ctx, normalize_trace_ctx
from netrep_tpu.serve.top import render, render_tenant_table, snapshot
from netrep_tpu.utils.config import EngineConfig

CFG = EngineConfig(chunk_size=16, autotune=False)

COST_FIELDS = ("device_s", "transfer_s", "perms", "bytes_to_host",
               "compile_s_amortized")


@pytest.fixture(scope="module")
def fx():
    mixed = make_mixed_pair(100, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    return dict(dn=dn, dc=dc, dd=dd, tn=tn, tc=tc, td=td, assign=assign)


def make_server(fx, tmp_path, *, tenants=("a",), start=True, tel="tel",
                **cfg_kw):
    cfg_kw.setdefault("engine", CFG)
    cfg_kw.setdefault("telemetry", str(tmp_path / f"{tel}.jsonl"))
    srv = PreservationServer(ServeConfig(**cfg_kw), start=start)
    client = InProcessClient(srv)
    for t in tenants:
        client.register_dataset(t, "d", network=fx["dn"],
                                correlation=fx["dc"], data=fx["dd"],
                                assignments=fx["assign"])
        client.register_dataset(t, "t", network=fx["tn"],
                                correlation=fx["tc"], data=fx["td"])
    return srv, client


def read_events(path):
    return [json.loads(l) for l in open(path, encoding="utf-8")]


def assert_conserved(costs: list[dict]):
    """The pinned contract: member costs sum bit-exactly (f64, ``==``)
    to the pack totals on every field."""
    assert costs, "no member costs to check"
    totals = costs[0]["pack_totals"]
    for c in costs[1:]:
        assert c["pack_totals"] == totals, "members disagree on totals"
    for f in COST_FIELDS:
        s = costs[0][f]
        for c in costs[1:]:
            s = s + c[f]
        assert s == totals[f], (f, s, totals[f])


# ---------------------------------------------------------------------------
# conservation: fixed-n, mixed budgets, adaptive, deadline expiry
# ---------------------------------------------------------------------------

def test_fixed_n_pack_costs_conserve_bit_exactly(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, tenants=("a", "b"),
                              start=False)
    h1 = client.submit("a", "d", "t", n_perm=64, seed=3)
    h2 = client.submit("b", "d", "t", n_perm=32, seed=11)
    srv.start()
    try:
        r1 = client.result(h1, timeout=600)
        r2 = client.result(h2, timeout=600)
    finally:
        srv.close()
    assert r1["pack_size"] == 2 and r2["pack_size"] == 2
    assert_conserved([r1["cost"], r2["cost"]])
    # perms = the dispatched permutations each member consumed: the
    # 32-perm member leaves the shared dispatch at its ceiling
    assert r1["cost"]["perms"] == 64 and r2["cost"]["perms"] == 32
    # bytes are exactly proportional to live modules x perms (equal
    # module counts here): the deeper member moved more
    assert r1["cost"]["bytes_to_host"] == 2 * r2["cost"]["bytes_to_host"]
    assert r1["cost"]["device_s"] > 0.0
    # the identity-totals stay within float-noise of the raw measurement
    tot = r1["cost"]["pack_totals"]
    assert tot["device_s"] > 0.0


def test_adaptive_member_costs_conserve(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, start=False)
    h1 = client.submit("a", "d", "t", n_perm=64, seed=3)
    h2 = client.submit("a", "d", "t", n_perm=64, seed=5, adaptive=True)
    srv.start()
    try:
        r1 = client.result(h1, timeout=600)
        r2 = client.result(h2, timeout=600)
    finally:
        srv.close()
    assert r1["pack_size"] == 2
    assert_conserved([r1["cost"], r2["cost"]])


def test_expired_member_cost_is_attributed_and_conserves(fx, tmp_path):
    """A deadline-cancelled member consumed dispatches before its exit:
    its share is emitted via ``request_cost`` (the waiter only sees the
    error) and the pack still conserves — expired + survivor == totals."""
    srv, client = make_server(fx, tmp_path, start=False)
    h_ok = client.submit("a", "d", "t", n_perm=48, seed=3, deadline_s=600)
    h_exp = client.submit("a", "d", "t", n_perm=1_000_000, seed=5,
                          deadline_s=0.2)
    srv.start()
    try:
        res = client.result(h_ok, timeout=600)
        with pytest.raises(ServeError, match="deadline exceeded"):
            client.result(h_exp, timeout=600)
        tel = srv.config.telemetry
    finally:
        srv.close()
    ev = read_events(tel)
    costs = [e["data"] for e in ev if e["ev"] == "request_cost"]
    assert len(costs) == 2
    # JSON round-trips f64 exactly (shortest-repr), so the event-side
    # sums hit the same bits as the in-process ones
    totals = res["cost"]["pack_totals"]
    for f in COST_FIELDS:
        s = costs[0][f]
        for c in costs[1:]:
            s = s + c[f]
        assert s == totals[f], (f, s, totals[f])
    # the expired member's device time is non-zero: it ran before expiry
    exp_cost = next(c for c in costs
                    if c["perms"] != res["cost"]["perms"])
    assert exp_cost["device_s"] > 0.0
    # tenant rollup counted BOTH (expired work is not vanished work)
    st = srv.stats()
    assert st["tenants"]["a"]["cost"]["device_s"] == totals["device_s"]


def test_pack_monitor_split_is_exact_on_synthetic_weights():
    """Unit-level conservation: hand-fed chunks with awkward weights and
    costs still sum bit-exactly, and integer fields split by largest
    remainder (no byte ever lost or minted)."""
    from netrep_tpu.serve.packer import RequestPlan

    plans = []
    base = 0
    for k in (3, 2, 1):
        p = RequestPlan(labels=list(range(k)), specs=[None] * k,
                        counts={}, pool=np.arange(8), n_perm=100, seed=0)
        p.base = base
        base += k
        plans.append(p)
    mon = PackMonitor.__new__(PackMonitor)
    mon.plans = plans
    mon._cost_enabled = True
    mon._cost_chunks = [
        {"take": 7, "live": {0: 3, 1: 2, 2: 1}, "bytes": 1000,
         "dispatch_s": 0.7, "transfer_s": 0.013},
        {"take": 7, "live": {0: 3, 2: 1}, "bytes": 997,
         "dispatch_s": 0.1, "transfer_s": 0.007},
        {"take": 3, "live": {2: 1}, "bytes": 331,
         "dispatch_s": 0.05, "transfer_s": 0.001},
    ]
    out = mon.request_costs()
    members, totals = out["members"], out["totals"]
    for f in COST_FIELDS:
        s = members[0][f]
        for m in members[1:]:
            s = s + m[f]
        assert s == totals[f], (f, s, totals[f])
    assert totals["bytes_to_host"] == 1000 + 997 + 331
    assert totals["perms"] == (7 + 7) + 7 + (7 + 7 + 3)
    assert members[1]["perms"] == 7          # plan 1 retired after chunk 1
    # compile estimate: first dispatch minus steady median, attributed
    assert out["measured_device_s"] == pytest.approx(0.85)


def test_cost_off_without_telemetry_and_result_shape(fx, tmp_path):
    """Telemetry-off is the PR 12 path bit-identically: no cost tracking
    armed, no ``cost`` key in results, no telemetry file written."""
    srv, client = make_server(fx, tmp_path, telemetry=None)
    try:
        res = client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
    finally:
        srv.close()
    assert "cost" not in res
    assert res["completed"] == 32
    assert not list(tmp_path.glob("*.jsonl"))


# ---------------------------------------------------------------------------
# trace context: minting, span stamping, journal continuity
# ---------------------------------------------------------------------------

def test_trace_ctx_normalization():
    ctx = mint_trace_ctx()
    assert normalize_trace_ctx(ctx) == ctx
    assert normalize_trace_ctx({"trace": "xyz!"}) is None
    assert normalize_trace_ctx("nope") is None
    assert normalize_trace_ctx({"trace": "a" * 32, "parent": 7}) == {
        "trace": "a" * 32, "parent": None,
    }


def test_client_minted_trace_rides_request_subtree(fx, tmp_path):
    """The caller's trace id lands on the request span, propagates to the
    whole request subtree (request_packed / request_cost / request_done),
    and comes back in the result."""
    from netrep_tpu.utils.trace import build_span_tree

    ctx = mint_trace_ctx(parent_span="client-span-1")
    srv, client = make_server(fx, tmp_path)
    try:
        res = client.analyze("a", "d", "t", n_perm=32, seed=3,
                             trace_ctx=ctx, timeout=600)
        tel = srv.config.telemetry
    finally:
        srv.close()
    assert res["trace"] == ctx["trace"]
    ev = read_events(tel)
    recv = [e for e in ev if e["ev"] == "request_received"]
    assert recv[0]["data"]["trace"] == ctx["trace"]
    assert recv[0]["data"]["trace_parent"] == "client-span-1"
    spans, instants = build_span_tree(ev)
    req_sid = recv[0]["data"]["span"]
    assert spans[req_sid]["args"]["trace"] == ctx["trace"]
    # every node of the request's subtree inherited the trace id
    subtree = [s for s in spans.values() if s["parent"] == req_sid]
    for node in subtree:
        assert node["args"]["trace"] == ctx["trace"]
    sub_instants = [i for i in instants if i["parent"] == req_sid]
    assert any(i["name"] == "request_packed" for i in sub_instants)
    # request_cost is a point event under the request span carrying it
    costs = [e for e in ev if e["ev"] == "request_cost"]
    assert costs[0]["data"]["trace"] == ctx["trace"]
    assert costs[0]["data"]["parent"] == req_sid


def test_trace_ctx_journaled_with_accepted_record(fx, tmp_path):
    from netrep_tpu.serve import journal as jnl

    jpath = str(tmp_path / "j.jsonl")
    ctx = mint_trace_ctx()
    srv, client = make_server(fx, tmp_path, start=False, journal=jpath)
    client.submit("a", "d", "t", n_perm=32, seed=1, idempotency_key="k1",
                  trace_ctx=ctx)
    srv.close(drain=False)
    rec = jnl.scan(jpath)["pending"][0]
    assert rec["trace"] == ctx


def test_malformed_trace_ctx_never_fails_the_request(fx, tmp_path):
    srv, client = make_server(fx, tmp_path)
    try:
        res = client.analyze("a", "d", "t", n_perm=32, seed=3,
                             trace_ctx={"bogus": True}, timeout=600)
    finally:
        srv.close()
    # the server minted its own id instead of erroring
    assert isinstance(res["trace"], str) and len(res["trace"]) == 32


# ---------------------------------------------------------------------------
# the `top` ops surface (in-process tier-1, acceptance-pinned)
# ---------------------------------------------------------------------------

def test_top_snapshot_tenant_rows_from_live_server(fx, tmp_path):
    """`top --once --json` == ``snapshot(stats)`` + json.dumps: tenant
    rows carry queue depth, pinned-bucket p50/p99, attributed device
    time, brownout, and burn rate — from a live in-process daemon."""
    srv, client = make_server(fx, tmp_path, tenants=("a", "b"))
    try:
        client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
        client.analyze("b", "d", "t", n_perm=32, seed=5, timeout=600)
        snap = snapshot(srv.stats())
    finally:
        srv.close()
    json.dumps(snap)                       # the --json wire shape
    assert snap["brownout"] is False and snap["packs"] >= 1
    assert snap["uptime_s"] > 0
    rows = {r["tenant"]: r for r in snap["tenants"]}
    assert set(rows) == {"a", "b"}
    for r in rows.values():
        assert r["queue_depth"] == 0 and r["done"] == 1
        assert r["p50_ms"] is not None and r["p99_ms"] >= r["p50_ms"]
        assert r["device_s"] > 0.0 and r["device_s_per_s"] > 0.0
        assert r["burn_rate"] == 0.0
    text = render(snap)
    assert "netrep serve" in text and "a" in text and "burn" in text
    # the shared renderer tolerates missing quantiles (fresh tenants)
    table = render_tenant_table([{"tenant": "x"}])
    assert "x" in table and "-" in table


def test_slo_burn_rate_counts_misses(fx, tmp_path):
    """A deadline miss (and any terminal failure) burns the SLO budget:
    with budget 0.5 and one miss out of two requests, burn = 1.0."""
    srv, client = make_server(fx, tmp_path, start=False, slo_budget=0.5)
    h_ok = client.submit("a", "d", "t", n_perm=32, seed=3, deadline_s=600)
    h_exp = client.submit("a", "d", "t", n_perm=1_000_000, seed=5,
                          deadline_s=0.2)
    srv.start()
    try:
        client.result(h_ok, timeout=600)
        with pytest.raises(ServeError):
            client.result(h_exp, timeout=600)
        st = srv.stats()
    finally:
        srv.close()
    assert st["tenants"]["a"]["burn_rate"] == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# exposition golden shape (pinned buckets, per-tenant labels)
# ---------------------------------------------------------------------------

def test_metrics_text_new_series_golden_shape(fx, tmp_path):
    from netrep_tpu.utils.telemetry import COST_BUCKETS_S, LATENCY_BUCKETS_S

    srv, client = make_server(fx, tmp_path)
    try:
        client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
        text = srv.metrics_text()
    finally:
        srv.close()
    lines = text.splitlines()
    assert "# TYPE netrep_serve_latency_seconds histogram" in lines
    assert "# TYPE netrep_serve_request_device_seconds histogram" in lines
    # every pinned boundary appears as a cumulative le label, in order,
    # plus +Inf — the exact exposition downstream quantiles key on
    lat = [l for l in lines
           if l.startswith('netrep_serve_latency_seconds_bucket')]
    want = [f'le="{b:g}"' for b in LATENCY_BUCKETS_S] + ['le="+Inf"']
    assert len(lat) == len(want)
    for line, le in zip(lat, want):
        assert le in line and 'tenant="a"' in line
    cost = [l for l in lines
            if l.startswith('netrep_serve_request_device_seconds_bucket')]
    assert len(cost) == len(COST_BUCKETS_S) + 1
    assert ('netrep_serve_latency_seconds_count{tenant="a"} 1' in lines)
    assert ('netrep_serve_request_device_seconds_count{tenant="a"} 1'
            in lines)
    assert any(l.startswith(
        'netrep_serve_attributed_device_seconds_total{tenant="a"}')
        for l in lines)
    assert any(l.startswith(
        'netrep_serve_attributed_perms_total{tenant="a"} 32')
        for l in lines)
    assert any(l.startswith('netrep_serve_slo_burn_rate{tenant="a"} 0')
               for l in lines)


# ---------------------------------------------------------------------------
# telemetry --follow (the shared renderer)
# ---------------------------------------------------------------------------

def test_telemetry_follow_renders_events_and_tenant_table(fx, tmp_path,
                                                         capsys):
    from netrep_tpu.__main__ import _telemetry_follow

    srv, client = make_server(fx, tmp_path)
    try:
        client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
        tel = srv.config.telemetry
    finally:
        srv.close()
    assert _telemetry_follow(tel, poll_s=0.0, max_polls=1) == 0
    out = capsys.readouterr().out
    assert "request_received" in out and "request_cost" in out
    # the exit summary reuses top's tenant-table renderer
    assert "tenant" in out and "dev_s" in out
