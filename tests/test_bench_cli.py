"""Smoke-level CI guard for the bench CLI combinations the TPU watcher
queue runs on tunnel recovery (benchmarks/tpu_watch.sh): a watcher step
that crashes with the tunnel alive is skipped permanently after one retry,
so a broken flag combination would silently cost a BASELINE row. Each case
runs `bench.py --smoke` in a subprocess on the CPU backend and asserts one
parseable JSON result line.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from netrep_tpu.utils.backend import host_cpu_fingerprint as _fp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every flag combination the watcher queue uses (plus native, which the
# queue omits — it needs no TPU — but BASELINE rows rely on)
CASES = [
    [],
    ["--dtype", "bfloat16"],
    ["--derived-net"],
    ["--dtype", "bfloat16", "--derived-net"],
    ["--gather-mode", "fused"],
    ["--gather-mode", "fused", "--dtype", "bfloat16", "--derived-net"],
    ["--cap-granularity", "8"],
    ["--config", "B"],
    ["--config", "C"],
    # the watcher's reduced-genes C step; --genes must be passed WITHOUT
    # --smoke to exercise the flag (smoke clobbers it), so keep perms tiny
    ["--config", "C", "--genes", "900", "--modules", "4", "--perms", "32",
     "--samples", "24"],
    ["--config", "D"],
    ["--config", "D", "--derived-net"],
    ["--config", "E"],
    ["--config", "native"],
    # the pure-NumPy oracle row — the CPU denominator BASELINE.md's
    # speedup claims divide by; not in the watcher queue (needs no TPU)
    # but a silent break would cost the baseline side of every comparison
    ["--config", "oracle"],
    ["--config", "adaptive"],
    # streaming-executor row (ISSUE 2): counts parity is asserted inside
    # the bench, so this smoke case also guards the superchunk dispatch
    # path end-to-end
    ["--config", "superchunk"],
    # serve load generator (ISSUE 7): served/direct bit-parity is asserted
    # inside the bench before any row is emitted, so this smoke case also
    # guards the packing + warm-pool + scheduler path end-to-end
    ["--config", "serve"],
    # fused-statistics mega-kernel (ISSUE 8): counts parity vs the XLA
    # composition is asserted in-bench (interpret mode on CPU) before any
    # row, so this smoke case guards the stat_mode='fused' dispatch path
    # end-to-end
    ["--config", "pallas"],
    # atlas tiled network plane (ISSUE 9): tile-grid construction +
    # data-only null mechanism row — guards the TiledNetwork builder and
    # the correlation=None/network=None engine path end-to-end (the
    # opt-in ATLAS_STEP watcher step runs this config on TPU; it now
    # also emits the ISSUE 11 screening pair after the PR 9 row)
    ["--config", "atlas"],
    # exact tile screening (ISSUE 11): the screened-vs-unscreened pair
    # alone — screened/unscreened bit-parity is asserted in-bench before
    # any row, so this smoke case guards the screen → refine → dispatch
    # restructure and the device-side τ/top-k selection end-to-end
    ["--config", "atlas", "--screen-only"],
    # mixed-precision null screening (ISSUE 16): bf16-vs-f32 bit-parity of
    # tail counts (materialized AND streaming) is asserted in-bench before
    # any row, so this smoke case guards the screened chunk program, the
    # rescue worklist dispatch, and the null_precision plumbing end-to-end
    ["--config", "mixed"],
    # all-pairs grid atlas (ISSUE 17): per-cell bit-identity to the solo
    # runs AND the <25% incremental-delta bound are asserted in-bench
    # before any row, so this smoke case guards the cross-pair packing,
    # observed-stat dedup, manifest reuse, and warm-start prior path
    # end-to-end
    ["--config", "grid"],
]


def _run_cpu_subprocess(cmd, timeout):
    """Shared subprocess harness: CPU platform + the suite's persistent
    compile cache (three tests were carrying this inline; a missed copy
    would silently run uncached and inflate CI toward the timeouts)."""
    return subprocess.run(
        cmd,
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            # fingerprinted subdir — the same dir enable_persistent_cache
            # resolves, so children share the suite's warm cache
            "JAX_COMPILATION_CACHE_DIR": os.path.join(
                REPO, ".jax_cache", _fp()
            ),
        },
        capture_output=True,
        text=True,
        timeout=timeout,
    )


@pytest.mark.slow
def test_sharded_microbench_smoke():
    """The watcher's `sharded` step: a crash with the tunnel alive is
    skipped permanently after one retry, so the script must run end-to-end
    on CPU at tiny shapes (same policy as the bench.py CASES)."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/microbench_sharded_gather.py",
         "--genes", "400", "--modules", "3", "--perms", "16",
         "--chunk", "8", "--samples", "16"],
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    rows = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    assert len(rows) == 3
    assert all(r["perms_per_sec"] > 0 for r in rows)


@pytest.mark.slow
def test_serve_kill_recover_smoke():
    """The watcher's SERVE_CRASH_DRILL load row (ISSUE 10): a journaled
    server killed mid-pack, recovered, parity asserted in-bench; the row
    carries the `serve-recover` metric label (its own perf-ledger
    fingerprint class) and the re-served/recomputed split."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/serve_load.py", "--smoke",
         "--kill-recover"],
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"].startswith("serve-recover")
    assert row["time_to_recovery_s"] > 0
    assert row["requests_reserved"] >= 1      # answered from the journal
    assert row["requests_recomputed"] >= 1    # resumed/recomputed
    assert row["perms_per_sec"] > 0


@pytest.mark.slow
def test_serve_fleet_smoke():
    """The watcher's FLEET_DRILL load row (ISSUE 14): the mixed-tenant
    workload through a 2-replica in-process fleet with a mid-run replica
    kill — parity asserted in-bench; the row carries the `serve-fleet`
    metric label (its own perf-ledger fingerprint class), p50/p99, the
    measured failover time, and aggregate perms/s vs 1 replica."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/serve_load.py", "--smoke",
         "--fleet", "2"],
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"].startswith("serve-fleet")
    assert row["replicas"] == 2
    assert row["failover_s"] > 0            # the kill genuinely fired
    assert row["perms_per_sec"] > 0 and row["perms_per_sec_1replica"] > 0
    assert row["vs_1_replica"] > 0
    assert row["p99_ms"] >= row["p50_ms"] > 0
    # warm-start accounting (ISSUE 15): the fleet row reports the first
    # completed request's latency and the worst replica's first compile
    # span (+ source) against the PR 14 coldstart ledger baseline
    assert row["first_request_ms"] > 0
    assert row["coldstart_compile_s"] >= 0
    assert "coldstart_src" in row and "coldstart_baseline_s" in row


@pytest.mark.slow
def test_serve_autoscale_smoke():
    """The watcher's AUTOSCALE_DRILL load row (ISSUE 19): square-wave
    traffic through an autoscaled fleet (min 1, max peak) with forced
    noticed evictions landing mid-trace, against the same workload on a
    static peak fleet — parity asserted in-bench; the row carries the
    `serve-autoscale` metric label (its own perf-ledger fingerprint
    class) and gates zero lost requests, every eviction performed, and
    fewer replica-seconds than the static fleet via its exit code."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/serve_load.py", "--smoke",
         "--autoscale"],
        timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"].startswith("serve-autoscale")
    assert row["ok"] is True
    assert row["lost_requests"] == 0
    assert row["evictions"] == 2 and len(row["evicted"]) == 2
    assert row["replica_seconds"] < row["replica_seconds_static"]
    assert row["p99_ms"] > 0 and row["p99_static_ms"] > 0


@pytest.mark.slow
def test_serve_warmstart_smoke():
    """The watcher's WARMSTART step (ISSUE 15): cold fresh-process
    first-request compile span vs the same measurement against a
    warmup-populated store — `warm_ok` (source=aot, warm < cold) is
    asserted by the scenario's own exit code."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/serve_load.py", "--smoke",
         "--warmstart"],
        timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["metric"].startswith("serve-warmstart")
    assert row["warm_ok"] is True
    assert row["warm_source"] == "aot" and row["cold_source"] == "jit"
    assert row["value"] < row["cold_compile_span_s"]


def test_warmstart_bench_helpers(tmp_path):
    """Unit pins for the serve-warmstart scenario's parsers: the PR 14
    coldstart baseline is the median of matching ledger entries, and the
    per-replica compile-span scan keeps the worst FIRST-fingerprint span
    with its source."""
    import importlib

    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    sl = importlib.import_module("serve_load")

    ledger = tmp_path / "ledger.jsonl"
    rows = [
        {"perf_v": 1, "t": 1.0, "source": "serve", "round": None,
         "run": None, "fingerprint": f"serve-fleet-coldstart|r0|cpu",
         "backend": "cpu", "mode": "fleet-coldstart",
         "perms_per_sec": 10.0, "compile_s": s, "n_perm": 32,
         "metric": "serve-fleet coldstart r0"}
        for s in (1.0, 3.0, 2.0)
    ] + [{"perf_v": 1, "t": 1.0, "source": "serve", "round": None,
          "run": None, "fingerprint": "other", "backend": "cpu",
          "mode": None, "perms_per_sec": 5.0, "compile_s": 99.0,
          "n_perm": 8, "metric": "x"}]
    ledger.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert sl._coldstart_baseline(str(ledger)) == 2.0   # median, no mixing
    assert sl._coldstart_baseline(None) is None
    assert sl._coldstart_baseline(str(tmp_path / "missing")) is None

    tel = tmp_path / "r0_tel.jsonl"
    evs = [
        {"v": 1, "t": 1.0, "m": 0.0, "run": "x", "ev": "compile_span",
         "data": {"s": 0.8, "key": "k1", "source": "jit"}},
        {"v": 1, "t": 2.0, "m": 0.0, "run": "x", "ev": "compile_span",
         "data": {"s": 5.0, "key": "k1", "source": "jit"}},  # repeat key
        {"v": 1, "t": 3.0, "m": 0.0, "run": "x", "ev": "compile_span",
         "data": {"s": 1.2, "key": "k2", "source": "aot"}},
    ]
    tel.write_text("\n".join(json.dumps(e) for e in evs) + "\n")
    worst, src = sl._first_compile_spans([str(tel)])
    assert worst == 1.2 and src == "aot"   # repeat-key span never counts


@pytest.mark.slow
def test_bf16_drift_smoke():
    """The watcher's `bf16_drift` step at tiny shapes: one parseable JSON
    line with the per-statistic drift table."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/bf16_drift.py",
         "--genes", "400", "--modules", "3", "--perms", "16",
         "--samples", "16"],
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert "per_statistic" in row and len(row["per_statistic"]) == 7
    assert np.isfinite(row["max_abs_drift"])


def test_parity_only_gate_refuses_cpu_pass():
    """The watcher's fused-parity gate records 'parity PASS' only on exit 0.
    On CPU the kernel runs in the Pallas *interpreter* — no Mosaic proof —
    so --parity-only must pass the parity assertions yet still exit nonzero
    (3), or a probe-race CPU drop would permanently unlock the fused
    decision steps without the kernel ever compiling on a chip."""
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/microbench_parts.py", "--parity-only",
         "--genes", "600", "--K", "2", "--batch", "2"],
        timeout=580,
    )
    assert proc.returncode == 3, (proc.returncode, proc.stdout[-2000:],
                                  proc.stderr[-2000:])
    # the parity checks themselves must have RUN and passed before the
    # deliberate nonzero exit — both dtypes
    assert proc.stdout.count("ok") >= 2, proc.stdout[-2000:]
    assert "FAILED" not in proc.stdout, proc.stdout[-2000:]


def test_tune_sweep_resumes_from_state(tmp_path):
    """A tunnel death mid-tune must only cost the in-flight point: completed
    points persist to --state keyed by the full sweep+point params (plus a
    code fingerprint, so stale rows from an older engine never replay) and
    are reused verbatim (printed with cached:true) on rerun. Pre-caching
    the ENTIRE grid makes the rerun pure replay — seconds, no measuring —
    and pins best-selection across cached rows."""
    import importlib

    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    tune = importlib.import_module("tune_northstar")
    sweep = {"perms": 16, "genes": 500, "modules": 3, "samples": 16,
             "code": tune.code_fingerprint()}

    def entry(pps, chunk=256, pb=None, dt="float32", gm="mxu",
              derived=False, cap_g=None):
        label = {"chunk": chunk, "perm_batch": pb, "dtype": dt,
                 "gather_mode": gm, "derived_net": derived,
                 "power_iters": 40,
                 **({"cap_granularity": cap_g} if cap_g else {}),
                 "device": None}
        key = json.dumps({**sweep, **label}, sort_keys=True)
        row = {**label, "device": "TPU v5 lite0", "s": 1.0,
               "perms_per_sec": pps, "ok": True}
        return json.dumps({"key": key, "row": row})

    lines = []
    # stage 1: the full 8-point decision grid; mxu/f32/plain wins at 999
    import itertools
    for gm, dt, derived in itertools.product(
        ["mxu", "fused"], ["float32", "bfloat16"], [False, True]
    ):
        win = gm == "mxu" and dt == "float32" and not derived
        lines.append(entry(999.0 if win else 111.0, gm=gm, dt=dt,
                           derived=derived))
    # stage 2 refinements around the winner + the cap-granularity point
    for chunk, pb in [(128, None), (512, None), (256, 4), (256, 64)]:
        lines.append(entry(222.0, chunk=chunk, pb=pb))
    lines.append(entry(333.0, cap_g=8))
    state = tmp_path / "tune_state.jsonl"
    state.write_text("\n".join(lines) + "\n")

    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/tune_northstar.py", "--genes", "500",
         "--modules", "3", "--samples", "16", "--perms", "16",
         "--state", str(state)],
        timeout=580,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = [json.loads(l) for l in proc.stdout.strip().splitlines()
           if l.startswith("{")]
    cached = [l for l in out if l.get("cached")]
    assert len(cached) == 13, (len(cached), proc.stdout[-2000:])
    best = [l for l in out if "best" in l][-1]["best"]
    assert best["perms_per_sec"] == 999.0, best
    # CPU rows must never be written back into the resume state
    entries = [json.loads(l) for l in state.read_text().splitlines()]
    assert len(entries) == 13, len(entries)


@pytest.mark.slow
def test_tune_sweep_runs_end_to_end_on_cpu():
    # the decision grid (benchmarks/tune_northstar.py) is the highest-value
    # step in the watcher queue after the headline row; a crash with the
    # tunnel alive skips it permanently after one retry, so its full
    # point-loop (mxu/fused x f32/bf16 x derived-net + refinement +
    # granularity + exactness pricing) must be CI-proven like the bench
    # CLI combos
    proc = _run_cpu_subprocess(
        [sys.executable, "benchmarks/tune_northstar.py", "--genes", "500",
         "--modules", "3", "--samples", "16", "--perms", "16"],
        timeout=580,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    lines = [json.loads(l) for l in proc.stdout.strip().splitlines()
             if l.startswith("{")]
    best = [l for l in lines if "best" in l]
    assert best and best[-1]["best"] is not None, proc.stdout[-2000:]
    ok_points = [l for l in lines if l.get("ok")]
    assert len(ok_points) >= 12, (len(ok_points), proc.stdout[-2000:])


@pytest.mark.slow
def test_bench_adaptive_row_reports_both_passes():
    """The adaptive config's one-row contract (ISSUE r6 acceptance): the
    sequential-stopping wall-clock AND permutations-evaluated land beside
    the fixed-n numbers, with the decision-agreement verdict."""
    proc = _run_cpu_subprocess(
        [sys.executable, "bench.py", "--config", "adaptive", "--smoke"],
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["perms_evaluated_adaptive"] < row["perms_evaluated_fixed"]
    assert row["perm_reduction_x"] > 1.0
    assert row["value"] > 0 and row["fixed_s"] > 0
    assert row["decisions_agree_at_alpha05"] is True
    assert len(row["n_perm_used"]) > 0


def test_bench_shield_always_emits_a_row_on_hang():
    # a tunnel death mid-run blocks device calls forever; the shield must
    # kill the child and still end in ONE parseable JSON line with the
    # tpu_fallback marker (so the watcher reprobes instead of marking done,
    # and the driver's round-end artifact is never an opaque hang)
    if not os.path.exists("/root/.axon_site"):
        pytest.skip("no axon tunnel plumbing here: with JAX_PLATFORMS='' "
                    "tunnel_expected() is False and the shield never engages")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke"],
        cwd=REPO,
        env={**os.environ,
             # empty (NOT cpu): the shield only engages when the tunnel
             # could be dialed (tunnel_expected); an explicit cpu platform
             # bypasses it by design, which would turn this into a plain
             # smoke run
             "JAX_PLATFORMS": "",
             # sub-second so even a warm-cache smoke child cannot finish
             # before the shield kills it (both attempts must time out)
             "NETREP_BENCH_TIMEOUT": "0.3"},
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row.get("tpu_fallback") is True and "timed out" in row["error"], row


@pytest.mark.slow
def test_bench_config_d_resumes_from_checkpoint():
    # Config-D-shaped resumable smoke (VERDICT r3 item 6b): a partial
    # checkpoint left by a mid-run tunnel death must be resumed by the next
    # bench.py invocation (same stable path), the emitted row must say so,
    # and the file must be cleaned up on success. Shape is unique to this
    # test (not --smoke) so xdist neighbors can't race on the checkpoint.
    import tempfile


    sys.path.insert(0, REPO)
    import bench
    from netrep_tpu.parallel.engine import PermutationEngine
    from netrep_tpu.utils.config import EngineConfig

    genes, modules, samples, perms, chunk = 900, 4, 24, 48, 16
    (d_data, d_corr, d_net), (t_data, t_corr, t_net) = bench.build_problem(
        genes, modules, samples
    )
    specs = bench.make_specs(genes, modules, 30, 200)
    pool = np.arange(genes, dtype=np.int32)
    engine = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
        config=EngineConfig(chunk_size=chunk, power_iters=40,
                            gather_mode="auto"),
    )
    ck = os.path.join(
        tempfile.gettempdir(),
        f"netrep_bench_d_{genes}x{modules}x{samples}x{perms}.npz",
    )
    if os.path.exists(ck):
        os.remove(ck)
    # simulate the dead-tunnel partial run bench_d would leave behind:
    # same problem, same key=0 timing seed, a third of the permutations
    nulls, done = engine.run_null(chunk, key=0, checkpoint_path=ck,
                                  checkpoint_every=chunk)
    assert done == chunk and os.path.exists(ck)
    proc = _run_cpu_subprocess(
        [sys.executable, "bench.py", "--config", "D",
         "--genes", str(genes), "--modules", str(modules),
         "--samples", str(samples), "--perms", str(perms),
         "--chunk", str(chunk)],
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert f"resumed at {chunk}" in row["metric"], row
    assert row["value"] > 0, row
    assert not os.path.exists(ck)  # removed on success


@pytest.mark.slow
def test_bench_probe_fields_and_perf_ledger(tmp_path):
    """ISSUE 5 satellites: every metric row carries the structured
    backend-probe record (the round-5 120 s silent probe hang was prose
    only), the bench path emits its own ``backend_probe`` telemetry
    event, and a row with ``perms_per_sec`` feeds the perf ledger beside
    the engine loop's own entry."""
    ledger = str(tmp_path / "led.jsonl")
    tel = str(tmp_path / "tel.jsonl")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", "--telemetry", tel],
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "NETREP_PERF_LEDGER": ledger,
            "JAX_COMPILATION_CACHE_DIR": os.path.join(
                REPO, ".jax_cache", _fp()
            ),
        },
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    assert row["probe_outcome"] == "explicit_platform"
    assert isinstance(row["probe_s"], float)
    assert "fallback_reason" not in row  # CPU was explicit, not a fallback
    from netrep_tpu.utils import perfledger

    sources = {e["source"] for e in perfledger.read_entries(ledger)}
    assert sources == {"run", "bench"}
    ok, report = perfledger.check(ledger)
    assert ok, report
    probes = [json.loads(l) for l in open(tel)
              if '"backend_probe"' in l]
    assert any(p["data"].get("source") == "bench" for p in probes)
    # roofline provenance (ISSUE 18): every throughput row carries the
    # cost fields (None when no engine note was pending — never absent),
    # and the row that consumed the run's note carries the full block,
    # which the ledger entry picks up verbatim
    rows = [json.loads(l) for l in proc.stdout.strip().splitlines()
            if l.startswith("{")]
    pps_rows = [r for r in rows
                if isinstance(r.get("perms_per_sec"), (int, float))]
    assert pps_rows
    for r in pps_rows:
        assert "flops" in r and "bytes_hbm" in r and "utilisation" in r
    noted = [r for r in pps_rows if isinstance(r.get("roofline"), dict)]
    assert noted and isinstance(noted[0]["roofline"]["family"], str)
    assert isinstance(noted[0]["flops"], int)
    rl_entries = [e for e in perfledger.read_entries(ledger)
                  if e["source"] == "bench"
                  and isinstance(e.get("roofline"), dict)]
    assert rl_entries
    assert rl_entries[0]["roofline_v"] == perfledger.ROOFLINE_VERSION


@pytest.mark.slow
@pytest.mark.parametrize("flags", CASES, ids=lambda f: " ".join(f) or "default")
def test_bench_smoke_combination(flags):
    # --smoke clobbers --genes/--modules/--perms; cases that exercise the
    # explicit-shape flags (the watcher's reduced-genes C step) must run
    # without it and carry their own tiny shape
    cmd = [sys.executable, "bench.py"]
    if "--genes" not in flags:
        cmd.append("--smoke")
    proc = _run_cpu_subprocess([*cmd, *flags], timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    if row.get("error") == "no C++ toolchain":
        pytest.skip("no C++ toolchain on this machine")
    assert "metric" in row and "error" not in row, row
    assert row.get("value", 0) > 0 or "perms_per_sec_by_threads" in row, row


def test_multichip_ledger_fingerprints_split_per_mesh_size():
    """ISSUE 6 satellite: multichip rows carry the mesh size in the
    metric label, so the perf ledger groups each mesh size into its own
    history — `perf --check` can never judge a 1-device rate against a
    4-device one."""
    from netrep_tpu.utils import perfledger

    rows = [
        {"metric": f"multichip x{n}", "n_devices": n,
         "perms_per_sec": 100.0 * n, "device": "TFRT_CPU_0",
         "chunk": 128, "dtype": "float32"}
        for n in (1, 2, 4)
    ]
    fps = [perfledger.bench_fingerprint(r) for r in rows]
    assert len(set(fps)) == 3, fps
    entries = [perfledger.entry_from_bench_row(r) for r in rows]
    assert all(e is not None for e in entries)
    assert len({e["fingerprint"] for e in entries}) == 3
    # the scaling summary row (no top-level perms_per_sec) never lands
    # in the ledger — each point already did, under its own fingerprint
    assert perfledger.entry_from_bench_row(
        {"metric": "multichip scaling 1..4 devices",
         "rows": [{"n_devices": 1, "perms_per_sec": 100.0}]}
    ) is None


@pytest.mark.slow
def test_bench_multichip_emits_real_scaling_rows(tmp_path):
    """ISSUE 6 satellite, end to end: `bench.py --config multichip`
    produces measured (non-stub) per-mesh-size rows plus one scaling
    summary with efficiency vs the 1-device baseline."""
    ledger = str(tmp_path / "ledger.jsonl")
    proc = subprocess.run(
        [sys.executable, "bench.py", "--config", "multichip", "--smoke"],
        cwd=REPO,
        env={
            **os.environ, "JAX_PLATFORMS": "cpu",
            "NETREP_MULTICHIP_MAX": "2",
            "NETREP_PERF_LEDGER": ledger,
            "JAX_COMPILATION_CACHE_DIR": os.path.join(
                REPO, ".jax_cache", _fp()
            ),
        },
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    rows = [json.loads(l) for l in proc.stdout.splitlines()
            if l.strip().startswith("{")]
    points = [r for r in rows if r.get("n_devices")]
    assert {r["n_devices"] for r in points} == {1, 2}
    for r in points:
        assert r["perms_per_sec"] > 0 and r["value"] > 0, r
    summary = rows[-1]
    assert summary["metric"].startswith("multichip scaling")
    eff = {s["n_devices"]: s["efficiency"] for s in summary["rows"]}
    assert eff[1] == 1.0 and eff[2] is not None
    # children fed the ledger once per mesh size, split fingerprints
    fps = {json.loads(l)["fingerprint"] for l in open(ledger)}
    assert len(fps) == 2, fps
