"""Smoke-level CI guard for the bench CLI combinations the TPU watcher
queue runs on tunnel recovery (benchmarks/tpu_watch.sh): a watcher step
that crashes with the tunnel alive is skipped permanently after one retry,
so a broken flag combination would silently cost a BASELINE row. Each case
runs `bench.py --smoke` in a subprocess on the CPU backend and asserts one
parseable JSON result line.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every flag combination the watcher queue uses (plus native, which the
# queue omits — it needs no TPU — but BASELINE rows rely on)
CASES = [
    [],
    ["--dtype", "bfloat16"],
    ["--derived-net"],
    ["--dtype", "bfloat16", "--derived-net"],
    ["--gather-mode", "fused"],
    ["--gather-mode", "fused", "--dtype", "bfloat16", "--derived-net"],
    ["--config", "B"],
    ["--config", "C"],
    # the watcher's reduced-genes C step; --genes must be passed WITHOUT
    # --smoke to exercise the flag (smoke clobbers it), so keep perms tiny
    ["--config", "C", "--genes", "900", "--modules", "4", "--perms", "32",
     "--samples", "24"],
    ["--config", "D"],
    ["--config", "D", "--derived-net"],
    ["--config", "E"],
    ["--config", "native"],
]


@pytest.mark.slow
@pytest.mark.parametrize("flags", CASES, ids=lambda f: " ".join(f) or "default")
def test_bench_smoke_combination(flags):
    # --smoke clobbers --genes/--modules/--perms; cases that exercise the
    # explicit-shape flags (the watcher's reduced-genes C step) must run
    # without it and carry their own tiny shape
    cmd = [sys.executable, "bench.py"]
    if "--genes" not in flags:
        cmd.append("--smoke")
    proc = subprocess.run(
        [*cmd, *flags],
        cwd=REPO,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            # reuse the suite's persistent compile cache in the subprocess
            # (conftest sets it via in-process jax.config only)
            "JAX_COMPILATION_CACHE_DIR": os.path.join(REPO, ".jax_cache"),
        },
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    if row.get("error") == "no C++ toolchain":
        pytest.skip("no C++ toolchain on this machine")
    assert "metric" in row and "error" not in row, row
    assert row.get("value", 0) > 0 or "perms_per_sec_by_threads" in row, row
