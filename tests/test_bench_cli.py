"""Smoke-level CI guard for the bench CLI combinations the TPU watcher
queue runs on tunnel recovery (benchmarks/tpu_watch.sh): a watcher step
that crashes with the tunnel alive is skipped permanently after one retry,
so a broken flag combination would silently cost a BASELINE row. Each case
runs `bench.py --smoke` in a subprocess on the CPU backend and asserts one
parseable JSON result line.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every flag combination the watcher queue uses (plus native, which the
# queue omits — it needs no TPU — but BASELINE rows rely on)
CASES = [
    [],
    ["--dtype", "bfloat16"],
    ["--derived-net"],
    ["--dtype", "bfloat16", "--derived-net"],
    ["--gather-mode", "fused"],
    ["--gather-mode", "fused", "--dtype", "bfloat16", "--derived-net"],
    ["--config", "B"],
    ["--config", "C"],
    ["--config", "C", "--genes", "900"],
    ["--config", "D"],
    ["--config", "D", "--derived-net"],
    ["--config", "E"],
    ["--config", "native"],
]


@pytest.mark.slow
@pytest.mark.parametrize("flags", CASES, ids=lambda f: " ".join(f) or "default")
def test_bench_smoke_combination(flags):
    proc = subprocess.run(
        [sys.executable, "bench.py", "--smoke", *flags],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    row = json.loads(proc.stdout.strip().splitlines()[-1])
    if row.get("error") == "no C++ toolchain":
        pytest.skip("no C++ toolchain on this machine")
    assert "metric" in row and "error" not in row, row
    assert row.get("value", 0) > 0 or "perms_per_sec_by_threads" in row, row
