"""Sparse-path tests (Config E, BASELINE.json:11): representation
round-trips, kernel parity against the dense engine on densified graphs,
same-seed null equality (the two engines share the permutation-draw
contract), and the sparse user surface."""

import numpy as np
import pytest

import jax.numpy as jnp

from netrep_tpu.ops import oracle
from netrep_tpu.ops.sparse import SparseAdjacency
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.parallel.sparse import SparsePermutationEngine
from netrep_tpu.utils.config import EngineConfig


def _knn_problem(rng, n_disc=50, n_test=44, k=6, s_d=30, s_t=26,
                 module_sizes=(9, 7, 5), with_data=True):
    """Synthetic kNN-style sparse pair: planted module data, adjacency =
    top-k |corr| edges per node, symmetrized."""
    def build(n, s):
        x = rng.standard_normal((s, n))
        pos = 0
        for sz in module_sizes:
            latent = rng.standard_normal(s)
            x[:, pos:pos + sz] = latent[:, None] + 0.7 * x[:, pos:pos + sz]
            pos += sz
        corr = np.corrcoef(x, rowvar=False)
        aff = np.abs(corr)
        np.fill_diagonal(aff, 0.0)
        rows, cols, vals = [], [], []
        for i in range(n):
            top = np.argsort(aff[i])[-k:]
            rows.extend([i] * k)
            cols.extend(top.tolist())
            vals.extend(aff[i, top].tolist())
        adj = SparseAdjacency.from_coo(rows, cols, vals, n)
        return x, adj

    d_data, d_adj = build(n_disc, s_d)
    t_data, t_adj = build(n_test, s_t)
    specs, pos = [], 0
    for kk, sz in enumerate(module_sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(kk + 1), idx, idx))
        pos += sz
    pool = np.arange(n_test, dtype=np.int32)
    if not with_data:
        d_data = t_data = None
    return (d_adj, d_data), (t_adj, t_data), specs, pool


def test_coo_roundtrip_and_symmetrize(rng):
    n = 12
    rows = [0, 1, 2, 5, 7]
    cols = [1, 2, 3, 6, 8]
    vals = [0.5, 0.25, 1.0, 0.75, 0.3]
    adj = SparseAdjacency.from_coo(rows, cols, vals, n)
    dense = adj.to_dense()
    assert dense[0, 1] == 0.5 and dense[1, 0] == 0.5  # symmetrized
    np.testing.assert_allclose(dense, dense.T)
    # self-loops and explicit zeros dropped
    adj2 = SparseAdjacency.from_coo([3, 4], [3, 5], [9.0, 0.0], n)
    assert adj2.to_dense().sum() == 0.0
    # round-trip through from_dense
    adj3 = SparseAdjacency.from_dense(dense)
    np.testing.assert_allclose(adj3.to_dense(), dense)
    # out-of-range errors
    with pytest.raises(ValueError, match="out of range"):
        SparseAdjacency.from_coo([0], [99], [1.0], n)


def test_coo_conflicting_reciprocal_entries_stay_symmetric():
    """(i,j)=a given alongside (j,i)=b must not yield an asymmetric
    adjacency: conflicts resolve on the canonical undirected edge (last in
    input order wins) BEFORE mirroring (ADVICE r1)."""
    n = 6
    adj = SparseAdjacency.from_coo(
        [0, 1, 2, 3], [1, 0, 3, 2], [0.5, 0.9, 0.2, 0.4], n
    )
    dense = adj.to_dense()
    np.testing.assert_allclose(dense, dense.T)
    assert dense[0, 1] == dense[1, 0] == np.float32(0.9)  # later entry wins
    assert dense[2, 3] == dense[3, 2] == np.float32(0.4)
    # same-direction duplicates: still last-wins
    adj2 = SparseAdjacency.from_coo([0, 0], [1, 1], [0.1, 0.7], n)
    assert adj2.to_dense()[0, 1] == np.float32(0.7)
    assert adj2.to_dense()[1, 0] == np.float32(0.7)


@pytest.mark.parametrize("with_data", [True, False])
def test_sparse_observed_matches_dense_engine(rng, with_data):
    """On a densified graph the sparse engine's observed statistics must
    match the dense engine's — except the correlation statistics, which the
    sparse path derives from data on the fly rather than from a user matrix
    (with data they agree because the dense fixture's correlation IS the
    data correlation; without data they are NaN on the sparse side)."""
    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(
        rng, with_data=with_data
    )
    d_dense, t_dense = d_adj.to_dense(), t_adj.to_dense()
    d_corr = (
        np.corrcoef(d_data, rowvar=False) if with_data
        else np.eye(d_adj.n)
    )
    t_corr = (
        np.corrcoef(t_data, rowvar=False) if with_data
        else np.eye(t_adj.n)
    )

    sparse_eng = SparsePermutationEngine(
        d_adj, d_data, t_adj, t_data, specs, pool,
        config=EngineConfig(chunk_size=8),
    )
    dense_eng = PermutationEngine(
        d_corr, d_dense, d_data, t_corr, t_dense, t_data, specs, pool,
        config=EngineConfig(chunk_size=8),
    )
    so = sparse_eng.observed()
    do = dense_eng.observed()
    if with_data:
        np.testing.assert_allclose(so, do, rtol=2e-4, atol=2e-4)
    else:
        # avg.weight (0) and cor.degree (3) agree; the rest NaN on sparse
        np.testing.assert_allclose(so[:, [0, 3]], do[:, [0, 3]],
                                   rtol=2e-4, atol=2e-4)
        assert np.isnan(so[:, [1, 2, 4, 5, 6]]).all()


def test_sparse_null_equals_dense_null_same_seed(rng):
    """The sparse and dense engines share the permutation-draw contract
    (same fold_in keys → same pool shuffle → same node sets), so on a
    densified graph the same seed must give the same null to float32
    tolerance — kernel parity on thousands of random modules at once."""
    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    d_corr = np.corrcoef(d_data, rowvar=False)
    t_corr = np.corrcoef(t_data, rowvar=False)

    cfg = EngineConfig(chunk_size=16, summary_method="power", power_iters=60)
    sparse_eng = SparsePermutationEngine(
        d_adj, d_data, t_adj, t_data, specs, pool, config=cfg
    )
    dense_eng = PermutationEngine(
        d_corr, d_adj.to_dense(), d_data, t_corr, t_adj.to_dense(), t_data,
        specs, pool, config=cfg,
    )
    sn, sd = sparse_eng.run_null(48, key=3)
    dn, dd = dense_eng.run_null(48, key=3)
    assert sd == dd == 48
    np.testing.assert_allclose(sn, dn, rtol=5e-3, atol=5e-3)


def test_sparse_null_determinism_and_chunk_independence(rng):
    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    e1 = SparsePermutationEngine(
        d_adj, d_data, t_adj, t_data, specs, pool,
        config=EngineConfig(chunk_size=8),
    )
    e2 = SparsePermutationEngine(
        d_adj, d_data, t_adj, t_data, specs, pool,
        config=EngineConfig(chunk_size=16),
    )
    n1, _ = e1.run_null(32, key=11)
    n2, _ = e2.run_null(32, key=11)
    np.testing.assert_allclose(n1, n2, rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_sparse_null_invariant_under_cap_granularity(rng):
    # the sparse engine buckets via the same rounded_cap — padding changes
    # from cap_granularity must be inert in its masked kernels too. Needs a
    # module > 32 nodes: below that the power-of-two ramp makes both
    # granularities pick identical caps (38 -> cap 64 at g32, 40 at g8)
    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(
        rng, n_disc=60, n_test=54, module_sizes=(38, 7)
    )
    e1 = SparsePermutationEngine(
        d_adj, d_data, t_adj, t_data, specs, pool,
        config=EngineConfig(chunk_size=16),
    )
    e2 = SparsePermutationEngine(
        d_adj, d_data, t_adj, t_data, specs, pool,
        config=EngineConfig(chunk_size=16, cap_granularity=8),
    )
    # guard against vacuity: the two engines must actually pad differently
    assert {b.cap for b in e1.buckets} != {b.cap for b in e2.buckets}
    n1, _ = e1.run_null(24, key=13)
    n2, _ = e2.run_null(24, key=13)
    np.testing.assert_allclose(n1, n2, rtol=1e-5, atol=1e-6)


def test_sparse_api_end_to_end(rng, tmp_path):
    from netrep_tpu import sparse_module_preservation

    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    labels = np.full(d_adj.n, "0", dtype=object)
    pos = 0
    for kk, sz in enumerate((9, 7, 5)):
        labels[pos:pos + sz] = str(kk + 1)
        pos += sz
    d_names = [f"c{i}" for i in range(d_adj.n)]
    t_names = d_names[: t_adj.n]

    ckpt = str(tmp_path / "sparse_null.npz")
    res = sparse_module_preservation(
        d_adj, t_adj, labels,
        discovery_data=d_data, test_data=t_data,
        discovery_names=d_names, test_names=t_names,
        n_perm=200, seed=0, checkpoint_path=ckpt,
    )
    assert res.observed.shape == (3, 7)
    assert res.completed == 200
    assert np.isfinite(res.p_values).all()
    assert (res.p_values[:, 0] < 0.25).all()  # planted modules preserved
    assert res.n_vars_present.tolist() == [9, 7, 5]

    # resume from the finished checkpoint is a no-op with identical results
    res2 = sparse_module_preservation(
        d_adj, t_adj, labels,
        discovery_data=d_data, test_data=t_data,
        discovery_names=d_names, test_names=t_names,
        n_perm=200, seed=0, checkpoint_path=ckpt,
    )
    np.testing.assert_array_equal(res.nulls, res2.nulls)


def test_sparse_api_validation(rng):
    from netrep_tpu import sparse_module_preservation

    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    labels = np.full(d_adj.n, "1", dtype=object)

    with pytest.raises(TypeError, match="SparseAdjacency"):
        sparse_module_preservation(
            d_adj.to_dense(), t_adj, labels,
        )
    with pytest.raises(ValueError, match="same node count"):
        sparse_module_preservation(d_adj, t_adj, labels)
    with pytest.raises(ValueError, match="discovery_names length"):
        sparse_module_preservation(
            d_adj, t_adj, labels,
            discovery_names=["a"], test_names=["a"] * t_adj.n,
        )
    with pytest.raises(ValueError, match="missing"):
        sparse_module_preservation(
            d_adj, t_adj, {"c0": "1"},
            discovery_names=[f"c{i}" for i in range(d_adj.n)],
            test_names=[f"c{i}" for i in range(t_adj.n)],
        )
    with pytest.raises(ValueError, match="do not exist in the module"):
        sparse_module_preservation(
            d_adj, t_adj, labels,
            discovery_names=[f"c{i}" for i in range(d_adj.n)],
            test_names=[f"c{i}" for i in range(t_adj.n)],
            modules=["zebra"],
        )


def test_sparse_vs_oracle_topology(rng):
    """Direct oracle check for the sparse topology kernels on a densified
    module slice (avg.weight, weighted degree feeding cor.degree)."""
    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    dense = t_adj.to_dense()
    for m in specs:
        idx = np.asarray(m.test_idx)
        sub = dense[np.ix_(idx, idx)]
        want_avg = oracle.avg_edge_weight(sub)
        want_deg = oracle.weighted_degree(sub)

        from netrep_tpu.ops.sparse import sparse_module_topology

        nbr_rows = jnp.asarray(t_adj.nbr[idx])
        wgt_rows = jnp.asarray(t_adj.wgt[idx])
        got_avg, got_deg = sparse_module_topology(
            nbr_rows, wgt_rows, jnp.asarray(idx),
            jnp.ones(len(idx), dtype=np.float32),
        )
        np.testing.assert_allclose(float(got_avg), want_avg, rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(got_deg), want_deg, rtol=1e-5, atol=1e-6
        )


def test_sparse_api_dataset_names(rng):
    """ADVICE r1: the result records caller-supplied dataset names (plot
    labels / multi-result bookkeeping), defaulting to the placeholders."""
    from netrep_tpu import sparse_module_preservation

    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    labels = np.full(d_adj.n, "0", dtype=object)
    labels[:9] = "1"
    d_names = [f"c{i}" for i in range(d_adj.n)]
    t_names = d_names[: t_adj.n]
    kw = dict(
        discovery_names=d_names, test_names=t_names, n_perm=32, seed=0,
    )

    res = sparse_module_preservation(
        d_adj, t_adj, labels, discovery="cohortA", test="cohortB", **kw
    )
    assert res.discovery == "cohortA" and res.test == "cohortB"
    res2 = sparse_module_preservation(d_adj, t_adj, labels, **kw)
    assert res2.discovery == "discovery" and res2.test == "test"


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_sparse_precomputed_correlation_matches_densified(rng):
    """Precomputed sparse correlation (VERDICT r1 item 8): feeding the
    engine a neighbor-list correlation must equal the dense engine run on
    the densified correlation (absent pairs = 0, same convention as absent
    edges) — both observed and null, with and without data."""
    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)
    # sparsified correlation graphs: reuse the adjacency's edge pattern with
    # signed correlation values
    def corr_graph(data, adj):
        c = np.corrcoef(data, rowvar=False)
        rows, cols = np.nonzero(adj.to_dense())
        return SparseAdjacency.from_coo(rows, cols, c[rows, cols], adj.n)

    d_cg, t_cg = corr_graph(d_data, d_adj), corr_graph(t_data, t_adj)
    cfg = EngineConfig(chunk_size=16, summary_method="eigh")

    for with_data in (True, False):
        dd = d_data if with_data else None
        td = t_data if with_data else None
        sparse_eng = SparsePermutationEngine(
            d_adj, dd, t_adj, td, specs, pool, config=cfg,
            disc_corr=d_cg, test_corr=t_cg,
        )
        dense_eng = PermutationEngine(
            d_cg.to_dense(), d_adj.to_dense(), dd,
            t_cg.to_dense(), t_adj.to_dense(), td,
            specs, pool, config=cfg,
        )
        so, do = sparse_eng.observed(), dense_eng.observed()
        sn, s_done = sparse_eng.run_null(32, key=5)
        dn, d_done = dense_eng.run_null(32, key=5)
        assert s_done == d_done == 32
        if with_data:
            np.testing.assert_allclose(so, do, rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(sn, dn, rtol=5e-3, atol=5e-3)
        else:
            # four finite statistics: avg.weight(0), cor.cor(2),
            # cor.degree(3), avg.cor(5); the dense data-less convention
            # keeps avg.cor NaN, so compare it against a direct densified
            # computation instead
            np.testing.assert_allclose(so[:, [0, 2, 3]], do[:, [0, 2, 3]],
                                       rtol=2e-4, atol=2e-4)
            np.testing.assert_allclose(sn[:, :, [0, 2, 3]],
                                       dn[:, :, [0, 2, 3]],
                                       rtol=5e-3, atol=5e-3)
            assert np.isfinite(so[:, 5]).all()
            assert np.isfinite(sn[:, :, 5]).all()
            d_corr_dense = d_cg.to_dense()
            t_corr_dense = t_cg.to_dense()
            for mi, m in enumerate(specs):
                dsub = d_corr_dense[np.ix_(m.disc_idx, m.disc_idx)]
                tsub = t_corr_dense[np.ix_(m.test_idx, m.test_idx)]
                off = ~np.eye(m.size, dtype=bool)
                want = np.mean(np.sign(dsub[off]) * tsub[off])
                np.testing.assert_allclose(so[mi, 5], want, atol=2e-4)
            # the rest stay NaN (no data)
            assert np.isnan(so[:, [1, 4, 6]]).all()


def test_sparse_api_precomputed_correlation_dataless(rng):
    """User surface: data-less run with precomputed correlations produces 4
    finite statistics and validates its inputs."""
    from netrep_tpu import sparse_module_preservation

    (d_adj, d_data), (t_adj, t_data), specs, pool = _knn_problem(rng)

    def corr_graph(data, adj):
        c = np.corrcoef(data, rowvar=False)
        rows, cols = np.nonzero(adj.to_dense())
        return SparseAdjacency.from_coo(rows, cols, c[rows, cols], adj.n)

    d_cg, t_cg = corr_graph(d_data, d_adj), corr_graph(t_data, t_adj)
    labels = np.full(d_adj.n, "0", dtype=object)
    pos = 0
    for kk, sz in enumerate((9, 7, 5)):
        labels[pos:pos + sz] = str(kk + 1)
        pos += sz
    d_names = [f"c{i}" for i in range(d_adj.n)]
    t_names = d_names[: t_adj.n]

    res = sparse_module_preservation(
        d_adj, t_adj, labels,
        discovery_correlation=d_cg, test_correlation=t_cg,
        discovery_names=d_names, test_names=t_names,
        n_perm=64, seed=3,
    )
    finite_cols = [0, 2, 3, 5]
    assert np.isfinite(res.observed[:, finite_cols]).all()
    assert np.isfinite(res.p_values[:, finite_cols]).all()
    assert np.isnan(res.p_values[:, [1, 4, 6]]).all()
    # planted modules: preserved on the correlation statistics too
    assert (res.p_values[:, 0] < 0.25).all()

    with pytest.raises(ValueError, match="both disc_corr and test_corr|both"):
        sparse_module_preservation(
            d_adj, t_adj, labels, discovery_correlation=d_cg,
            discovery_names=d_names, test_names=t_names, n_perm=8,
        )
    with pytest.raises(ValueError, match="same .* nodes|SparseAdjacency"):
        bad = SparseAdjacency.from_coo([0], [1], [0.5], t_adj.n + 3)
        sparse_module_preservation(
            d_adj, t_adj, labels,
            discovery_correlation=d_cg, test_correlation=bad,
            discovery_names=d_names, test_names=t_names, n_perm=8,
        )


def test_sparse_network_properties_matches_dense(rng):
    """sparse_network_properties equals the dense network_properties on a
    densified graph (same oracle math; degree/avg_weight from neighbor
    lists), with and without data."""
    from netrep_tpu import sparse_network_properties
    from netrep_tpu.models.properties import network_properties

    (d_adj, d_data), _, specs, pool = _knn_problem(rng)
    names = [f"c{i}" for i in range(d_adj.n)]
    labels = {nm: "0" for nm in names}
    for m in specs:
        for i in m.disc_idx:
            labels[names[i]] = m.label

    try:
        import pandas as pd
    except Exception:
        pytest.skip("pandas required")
    dense_net = pd.DataFrame(d_adj.to_dense(), index=names, columns=names)
    # network_properties requires a correlation argument (dense surface
    # contract); the properties themselves don't read it
    dense_corr = pd.DataFrame(
        np.corrcoef(d_data, rowvar=False), index=names, columns=names
    )

    for with_data in (True, False):
        dat = d_data if with_data else None
        sp = sparse_network_properties(
            d_adj, data=dat, module_assignments=labels, names=names
        )
        dn = network_properties(
            network={"d": dense_net},
            correlation={"d": dense_corr},
            data={"d": pd.DataFrame(dat, columns=names)} if with_data else None,
            module_assignments=labels,
            discovery="d", test="d",
        )
        assert set(sp) == set(dn)
        for lab in sp:
            assert sp[lab]["node_names"] == dn[lab]["node_names"]
            np.testing.assert_allclose(sp[lab]["degree"], dn[lab]["degree"],
                                       atol=1e-6)
            np.testing.assert_allclose(sp[lab]["avg_weight"],
                                       dn[lab]["avg_weight"], atol=1e-6)
            if with_data:
                np.testing.assert_allclose(sp[lab]["coherence"],
                                           dn[lab]["coherence"], atol=1e-6)
                np.testing.assert_allclose(sp[lab]["summary"],
                                           dn[lab]["summary"], atol=1e-6)
                np.testing.assert_allclose(sp[lab]["contribution"],
                                           dn[lab]["contribution"], atol=1e-6)
            else:
                assert sp[lab]["summary"] is None
                assert np.isnan(sp[lab]["coherence"])

    with pytest.raises(TypeError, match="SparseAdjacency"):
        sparse_network_properties(d_adj.to_dense(), module_assignments=labels)
    with pytest.raises(ValueError, match="names length"):
        sparse_network_properties(d_adj, module_assignments=labels,
                                  names=["a"])


def test_sparse_network_properties_singletons_and_validation(rng):
    """Observation surface semantics (unlike the preservation path):
    singleton modules are reported (avg_weight NaN, degree [0]), and the
    documented errors fire."""
    from netrep_tpu import sparse_network_properties

    (d_adj, _d), _, _specs, _pool = _knn_problem(rng)
    labels = np.full(d_adj.n, "0", dtype=object)
    labels[0] = "solo"
    labels[1:4] = "trio"
    props = sparse_network_properties(d_adj, module_assignments=labels)
    assert set(props) == {"solo", "trio"}
    assert np.isnan(props["solo"]["avg_weight"])
    assert props["solo"]["degree"].tolist() == [0.0]
    assert np.isfinite(props["trio"]["avg_weight"])

    with pytest.raises(ValueError, match="module_assignments must be provided"):
        sparse_network_properties(d_adj)
    with pytest.raises(ValueError, match="do not exist"):
        sparse_network_properties(d_adj, module_assignments=labels,
                                  modules=["zebra"])
    with pytest.raises(ValueError, match="background label"):
        sparse_network_properties(
            d_adj, module_assignments=np.full(d_adj.n, "0", dtype=object)
        )


def test_from_scipy_roundtrip(rng):
    """scipy.sparse interop: the single-cell kNN lingua franca builds the
    same adjacency as the COO constructor, including symmetrization of a
    directed kNN graph."""
    from scipy import sparse as sp

    n = 30
    dense = np.zeros((n, n))
    r = np.random.default_rng(2)
    for i in range(n):
        nbrs = r.choice([j for j in range(n) if j != i], size=4, replace=False)
        dense[i, nbrs] = r.uniform(0.1, 1.0, size=4)   # directed kNN
    for fmt in ("csr", "csc", "coo"):
        adj = SparseAdjacency.from_scipy(getattr(sp, f"{fmt}_matrix")(dense))
        got = adj.to_dense()
        np.testing.assert_allclose(got, got.T)
        # union-with-transpose semantics: every directed edge appears in
        # both orientations
        assert ((got != 0) == ((dense != 0) | (dense.T != 0))).all()
    with pytest.raises(TypeError, match="scipy.sparse"):
        SparseAdjacency.from_scipy(dense)
    with pytest.raises(ValueError, match="square"):
        SparseAdjacency.from_scipy(sp.csr_matrix(np.ones((3, 5))))


def test_from_scipy_duplicate_coo_entries_sum():
    """scipy sums duplicate COO entries; from_scipy must match that."""
    from scipy import sparse as sp

    m = sp.coo_matrix(
        (np.array([1.0, 2.0]), (np.array([0, 0]), np.array([1, 1]))),
        shape=(3, 3),
    )
    adj = SparseAdjacency.from_scipy(m)
    assert adj.to_dense()[0, 1] == 3.0
