"""Tests for `combine_analyses` — the rebuild of the reference's
``combineAnalyses()`` (upstream ``R/combineAnalyses.R``): pooling null
distributions from permutation runs split across machines/sessions and
recomputing exact p-values over the combined count.
"""

import dataclasses

import numpy as np
import pytest

from netrep_tpu import combine_analyses, module_preservation
from netrep_tpu.models.results import PreservationResult
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.utils.config import EngineConfig

CFG = EngineConfig(chunk_size=64, summary_method="power", power_iters=50)


def _run(toy, seed, n_perm=120, simplify=True):
    d, t = toy["discovery"], toy["test"]
    return module_preservation(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=[toy["labels"][n] for n in d["names"]],
        discovery="disc",
        test="test",
        n_perm=n_perm,
        seed=seed,
        simplify=simplify,
        config=CFG,
    )


@pytest.fixture(scope="module")
def two_runs(toy_pair_module):
    return _run(toy_pair_module, seed=1), _run(toy_pair_module, seed=2)


def test_combine_concatenates_and_recomputes(two_runs):
    a, b = two_runs
    c = combine_analyses(a, b)
    assert isinstance(c, PreservationResult)
    assert c.completed == a.completed + b.completed
    assert c.n_perm == a.n_perm + b.n_perm
    assert c.nulls.shape == (c.completed, *a.nulls.shape[1:])
    np.testing.assert_array_equal(c.nulls[: a.completed], a.nulls[: a.completed])
    np.testing.assert_array_equal(c.nulls[a.completed :], b.nulls[: b.completed])
    np.testing.assert_array_equal(c.observed, a.observed)
    # p-values equal a direct computation over the pooled nulls
    expect = pv.permutation_pvalues(
        a.observed, c.nulls, a.alternative, total_nperm=a.total_space
    )
    np.testing.assert_allclose(c.p_values, expect, rtol=0, atol=0)


def test_combine_three_way(two_runs, toy_pair_module):
    a, b = two_runs
    c3 = _run(toy_pair_module, seed=3, n_perm=60)
    c = combine_analyses(a, b, c3)
    assert c.completed == a.completed + b.completed + c3.completed


def test_same_seed_rejected(toy_pair_module):
    a = _run(toy_pair_module, seed=7)
    b = _run(toy_pair_module, seed=7)
    with pytest.raises(ValueError, match="identical null"):
        combine_analyses(a, b)
    c = combine_analyses(a, b, allow_duplicate_nulls=True)
    assert c.completed == a.completed + b.completed
    # a same-seed run that was interrupted (prefix of the other's stream)
    # must be caught too, not just byte-identical whole blocks
    prefix = dataclasses.replace(b, completed=50)
    with pytest.raises(ValueError, match="identical null"):
        combine_analyses(a, prefix)


def _fake_result(nulls, total_space, seed_obs=0):
    rng = np.random.default_rng(seed_obs)
    n = nulls.shape[0]
    return PreservationResult(
        discovery="d", test="t", module_labels=["1"],
        observed=rng.standard_normal((1, 7)),
        nulls=nulls, p_values=np.zeros((1, 7)),
        n_vars_present=np.array([5]), prop_vars_present=np.array([1.0]),
        total_size=np.array([5]), alternative="greater",
        n_perm=n, completed=n, total_space=total_space,
    )


def test_small_space_chance_collisions_tolerated():
    # In a small finite permutation space, independent different-seed runs
    # legitimately draw the same assignment sometimes; a few shared rows must
    # not be mistaken for a duplicated seed. Space of 2520 with 120+120 draws
    # expects ~5.7 collisions.
    rng1, rng2 = np.random.default_rng(1), np.random.default_rng(2)
    a_rows = rng1.standard_normal((120, 1, 7))
    b_rows = rng2.standard_normal((120, 1, 7))
    b_rows[[3, 40, 77]] = a_rows[[10, 20, 30]]  # 3 chance collisions
    a = _fake_result(a_rows, total_space=2520.0)
    b = _fake_result(b_rows, total_space=2520.0)
    b.observed = a.observed  # same analysis
    c = combine_analyses(a, b)
    assert c.completed == 240
    # but a fully-duplicated stream still trips the detector in that space
    dup = _fake_result(a_rows.copy(), total_space=2520.0)
    dup.observed = a.observed
    with pytest.raises(ValueError, match="identical null"):
        combine_analyses(a, dup)


def test_unknown_space_tolerates_few_collisions():
    # results saved by an older release carry total_space=None; a couple of
    # shared rows (possible small-space chance collisions) must not reject
    # the combine, but a duplicated stream must
    rng1, rng2 = np.random.default_rng(3), np.random.default_rng(4)
    a_rows = rng1.standard_normal((100, 1, 7))
    b_rows = rng2.standard_normal((100, 1, 7))
    b_rows[[5, 60]] = a_rows[[1, 2]]
    a = _fake_result(a_rows, total_space=None)
    b = _fake_result(b_rows, total_space=None)
    b.observed = a.observed
    c = combine_analyses(a, b)
    assert c.completed == 200 and c.total_space is None
    dup = _fake_result(a_rows.copy(), total_space=None)
    dup.observed = a.observed
    with pytest.raises(ValueError, match="identical null"):
        combine_analyses(a, dup)


def test_empty_blocks_do_not_collide(two_runs):
    # two fully-interrupted runs (completed=0) share no permutations; their
    # empty null blocks must not trip the duplicate detector
    a, b = two_runs
    e1 = dataclasses.replace(a, completed=0)
    e2 = dataclasses.replace(b, completed=0)
    c = combine_analyses(e1, e2, a)
    assert c.completed == a.completed


def test_mismatched_analyses_rejected(two_runs):
    a, b = two_runs
    with pytest.raises(ValueError, match="at least two"):
        combine_analyses(a)
    wrong_pair = dataclasses.replace(b, test="other")
    with pytest.raises(ValueError, match="different dataset pairs"):
        combine_analyses(a, wrong_pair)
    wrong_alt = dataclasses.replace(b, alternative="less")
    with pytest.raises(ValueError, match="different alternatives"):
        combine_analyses(a, wrong_alt)
    wrong_obs = dataclasses.replace(b, observed=b.observed + 0.5)
    with pytest.raises(ValueError, match="observed statistics differ"):
        combine_analyses(a, wrong_obs)
    wrong_labels = dataclasses.replace(b, module_labels=list(b.module_labels)[::-1])
    with pytest.raises(ValueError, match="different module labels"):
        combine_analyses(a, wrong_labels)
    with pytest.raises(TypeError):
        combine_analyses(a, {"disc": {"test": b}})


def test_combine_nested_dicts(toy_pair_module):
    a = _run(toy_pair_module, seed=1, simplify=False)
    b = _run(toy_pair_module, seed=2, simplify=False)
    c = combine_analyses(a, b)
    assert set(c) == {"disc"} and set(c["disc"]) == {"test"}
    inner = c["disc"]["test"]
    assert inner.completed == a["disc"]["test"].completed + b["disc"]["test"].completed
    # mismatched keys
    with pytest.raises(ValueError, match="disagree on discovery"):
        combine_analyses(a, {"other": b["disc"]})


def test_interrupted_runs_pool_completed_only(two_runs):
    a, b = two_runs
    # simulate an interrupted second run: only 50 of 120 completed
    short = dataclasses.replace(b, completed=50)
    c = combine_analyses(a, short)
    assert c.completed == a.completed + 50
    np.testing.assert_array_equal(c.nulls[a.completed :], b.nulls[:50])


def test_total_space_roundtrip_and_conflict(two_runs, tmp_path):
    a, b = two_runs
    assert a.total_space is not None
    p = str(tmp_path / "a.npz")
    a.save(p)
    loaded = PreservationResult.load(p)
    assert loaded.total_space == a.total_space
    conflicting = dataclasses.replace(b, total_space=123.0)
    with pytest.raises(ValueError, match="permutation-space sizes"):
        combine_analyses(a, conflicting)
    # a None-space input defers to the recorded one
    none_space = dataclasses.replace(b, total_space=None)
    c = combine_analyses(a, none_space)
    assert c.total_space == a.total_space


def test_preserved_modules_call():
    import warnings

    nulls = np.zeros((10, 4, 7))
    r = PreservationResult(
        discovery="d", test="t", module_labels=["a", "b", "c", "d"],
        observed=np.ones((4, 7)), nulls=nulls,
        p_values=np.array([[0.001] * 7,            # clearly preserved
                           [0.001] * 6 + [0.2],    # one statistic fails
                           [np.nan] * 7,           # nothing computable
                           [0.001] * 6 + [0.02]]), # alpha/4 < 0.02 < alpha
        n_vars_present=np.array([5] * 4),
        prop_vars_present=np.ones(4), total_size=np.array([5] * 4),
        alternative="greater", n_perm=10, completed=10,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the all-NaN row must stay silent
        # module 'd' distinguishes Bonferroni (0.05/4) from unadjusted
        assert r.preserved_modules() == ["a"]
        assert r.preserved_modules(adjust="none") == ["a", "d"]
        assert r.preserved_modules(alpha=0.7, adjust="none") == ["a", "b", "d"]
    with pytest.raises(ValueError, match="adjust"):
        r.preserved_modules(adjust="fdr")


def test_to_frame_and_results_table(two_runs):
    pd = pytest.importorskip("pandas")
    from netrep_tpu import results_table

    a, _ = two_runs
    f = a.to_frame()
    assert list(f.columns) == ["discovery", "test", "module", "statistic",
                               "observed", "p_value", "n_vars_present",
                               "prop_vars_present", "total_size",
                               "n_perm_used"]
    assert len(f) == len(a.module_labels) * 7
    # fixed runs report the shared completed count per module
    assert (f.n_perm_used == a.completed).all()
    # a specific cell matches the wide frames
    row = f[(f.module == a.module_labels[0]) & (f.statistic == "avg.weight")]
    assert float(row.observed.iloc[0]) == a.observed[0, 0]
    assert float(row.p_value.iloc[0]) == a.p_values[0, 0]

    # nested dict input concatenates
    nested = {"disc": {"test": a}}
    t = results_table(nested)
    pd.testing.assert_frame_equal(t, f)
    assert results_table(a).equals(f)
    with pytest.raises(TypeError):
        results_table([a])
    with pytest.raises(TypeError):
        results_table({"disc": {"test": 42}})
    with pytest.raises(ValueError, match="no results"):
        results_table({})


def test_combine_refits_gpd_tail_over_pooled_nulls(two_runs):
    """ISSUE 16: tail p-values never pool additively — when any input
    carries computed `p_tail`, the combined result REFITS the GPD over
    the pooled null tail (equal to a direct fit on the concatenated
    array); inputs without tail columns combine to tail-less results."""
    a, b = two_runs
    plain = combine_analyses(a, b)
    assert plain.p_tail is None and plain.tail_ok is None
    a.tail_pvalues()
    c = combine_analyses(a, b)
    assert c.p_tail is not None and c.p_tail.shape == c.p_values.shape
    want_p, want_ok = pv.gpd_tail_pvalues(
        a.observed, c.nulls, a.alternative
    )
    np.testing.assert_array_equal(c.p_tail, want_p)
    np.testing.assert_array_equal(c.tail_ok, want_ok)
    # NaN exactly where the gate refused — the save/load contract
    assert np.isnan(c.p_tail[~c.tail_ok]).all()
