"""Checkpoint/resume tests (SURVEY.md §5 "Checkpoint / resume"): exact-resume
guarantee (resumed null is bit-identical to an uninterrupted run), fingerprint
and seed guards, atomic save, and the module_preservation wiring."""

import numpy as np
import pytest

from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils import checkpoint as ck
from netrep_tpu.utils.config import EngineConfig


def _engine(rng, chunk=8):
    n = 50
    x = rng.standard_normal((20, n))
    corr = np.corrcoef(x, rowvar=False)
    net = np.abs(corr) ** 2
    specs = [
        ModuleSpec("1", np.arange(0, 8, dtype=np.int32), np.arange(0, 8, dtype=np.int32)),
        ModuleSpec("2", np.arange(8, 14, dtype=np.int32), np.arange(8, 14, dtype=np.int32)),
    ]
    pool = np.arange(n, dtype=np.int32)
    return PermutationEngine(
        corr, net, x, corr, net, x, specs, pool,
        config=EngineConfig(chunk_size=chunk, summary_method="power"),
    )


def test_exact_resume(tmp_path, rng):
    eng = _engine(rng)
    path = str(tmp_path / "null.npz")

    # full uninterrupted run (no checkpoint)
    full, done = eng.run_null(40, key=3)
    assert done == 40

    # partial run: only 16 perms, checkpointed
    part, done = eng.run_null(16, key=3, checkpoint_path=path, checkpoint_every=8)
    assert done == 16
    saved = ck.load_null_checkpoint(path)
    assert saved["completed"] == 16

    # resume to 40 from the checkpoint: must equal the uninterrupted run
    resumed, done = eng.run_null(40, key=3, checkpoint_path=path)
    assert done == 40
    np.testing.assert_array_equal(resumed, full)


def test_shrinking_resume_honors_shape(tmp_path, rng):
    """Resuming with a smaller n_perm must return an (n_perm, ...) array."""
    eng = _engine(rng)
    path = str(tmp_path / "null.npz")
    eng.run_null(40, key=3, checkpoint_path=path)
    small, done = eng.run_null(12, key=3, checkpoint_path=path)
    assert small.shape[0] == 12
    assert done == 12
    assert np.isfinite(small).all()


def test_wrong_seed_refuses(tmp_path, rng):
    eng = _engine(rng)
    path = str(tmp_path / "null.npz")
    eng.run_null(16, key=3, checkpoint_path=path)
    with pytest.raises(ValueError, match="different PRNG key"):
        eng.run_null(32, key=4, checkpoint_path=path)


def test_wrong_problem_refuses(tmp_path, rng):
    eng = _engine(rng)
    path = str(tmp_path / "null.npz")
    eng.run_null(16, key=3, checkpoint_path=path)
    other = _engine(np.random.default_rng(9))  # same sizes → same fingerprint
    # different module sizes → different fingerprint
    n = 50
    x = rng.standard_normal((20, n))
    corr = np.corrcoef(x, rowvar=False)
    net = np.abs(corr) ** 2
    eng2 = PermutationEngine(
        corr, net, x, corr, net, x,
        [ModuleSpec("1", np.arange(5, dtype=np.int32), np.arange(5, dtype=np.int32))],
        np.arange(n, dtype=np.int32),
        config=EngineConfig(chunk_size=8),
    )
    with pytest.raises(ValueError, match="different problem"):
        eng2.run_null(32, key=3, checkpoint_path=path)
    del other


def test_completed_checkpoint_short_circuits(tmp_path, rng):
    eng = _engine(rng)
    path = str(tmp_path / "null.npz")
    a, _ = eng.run_null(24, key=0, checkpoint_path=path)
    # a fresh engine resumes from the finished checkpoint without recompute
    eng2 = _engine(np.random.default_rng(42))
    b, done = eng2.run_null(24, key=0, checkpoint_path=path)
    assert done == 24
    np.testing.assert_array_equal(a, b)


def test_module_preservation_checkpoint_dir(tmp_path, rng, toy_pair):
    import netrep_tpu

    tp = toy_pair

    def inputs():
        import pandas as pd

        def df(m, names):
            return pd.DataFrame(m, index=names, columns=names)

        return dict(
            network={"d": df(tp["discovery"]["network"], tp["discovery"]["names"]),
                     "t": df(tp["test"]["network"], tp["test"]["names"])},
            correlation={"d": df(tp["discovery"]["correlation"], tp["discovery"]["names"]),
                         "t": df(tp["test"]["correlation"], tp["test"]["names"])},
            module_assignments=tp["labels"],
            discovery="d", test="t",
        )

    res1 = netrep_tpu.module_preservation(
        **inputs(), n_perm=24, seed=5,
        checkpoint_dir=str(tmp_path), checkpoint_every=8,
    )
    files = list(tmp_path.glob("null_d__t.npz"))
    assert len(files) == 1
    # rerun resumes from the completed checkpoint and reproduces the result
    res2 = netrep_tpu.module_preservation(
        **inputs(), n_perm=24, seed=5,
        checkpoint_dir=str(tmp_path), checkpoint_every=8,
    )
    np.testing.assert_array_equal(res1.nulls, res2.nulls)
    np.testing.assert_array_equal(res1.p_values, res2.p_values)


def test_accept_degraded_fingerprint_scope(tmp_path, rng):
    """ISSUE 7 satellite, pinning the (now belt-only) degraded-acceptance
    scope: since format v4 made fingerprints mesh-shape-independent the
    scope's original trigger is gone, but its CONTRACT must hold for the
    legacy/third-party engines it still covers — inside the scope a
    fingerprint mismatch is accepted, while a PRNG key/seed mismatch
    STILL refuses (splicing two null streams is never right, degraded or
    not)."""
    eng = _engine(rng)
    path = str(tmp_path / "null.npz")
    eng.run_null(16, key=3, checkpoint_path=path)
    loaded = ck.load_null_checkpoint(path)
    kd = loaded["key_data"]
    fp = loaded["fingerprint"]
    other_fp = np.frombuffer(b"some-other-problem", dtype=np.uint8)
    other_kd = np.asarray(kd) + 1

    # outside any scope: fingerprint mismatch refuses
    with pytest.raises(ValueError, match="different problem"):
        ck.validate_identity(loaded, kd, other_fp, path)
    # inside the scope: fingerprint mismatch is accepted explicitly...
    with ck.accept_degraded_fingerprint("test_rebuild"):
        ck.validate_identity(loaded, kd, other_fp, path)
        # ...but a key/seed mismatch still ALWAYS raises — even when the
        # fingerprint matches exactly
        with pytest.raises(ValueError, match="different PRNG key"):
            ck.validate_identity(loaded, other_kd, fp, path)
        with pytest.raises(ValueError, match="different PRNG key"):
            ck.validate_identity(loaded, other_kd, other_fp, path)
    # the scope is dynamic, not sticky
    with pytest.raises(ValueError, match="different problem"):
        ck.validate_identity(loaded, kd, other_fp, path)


def test_foreign_npz_is_not_a_checkpoint(tmp_path):
    """A saved PreservationResult (or any foreign .npz) fed to the resume
    path raises an informative error, not a KeyError."""
    from netrep_tpu.utils import checkpoint as ckpt

    foreign = str(tmp_path / "foreign.npz")
    with open(foreign, "wb") as fh:
        np.savez(fh, result_version=np.int64(1))
    with pytest.raises(ValueError, match="not a null checkpoint"):
        ckpt.load_null_checkpoint(foreign)
