"""CI guard for the watcher-log summarizer's provenance rules (repo
convention: watcher-pipeline tooling is CI-proven — silent breakage costs
BASELINE rows). The drop/keep classifier is safety-critical: a CPU-timed
or failed row transcribed as a TPU number corrupts the decision grid."""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "summarize_watch", os.path.join(REPO, "benchmarks", "summarize_watch.py")
)
summarize_watch = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(summarize_watch)
classify = summarize_watch.classify


def test_classify_provenance_rules():
    tpu = "TPU v5 lite"
    cases = [
        # clean TPU rows
        ({"metric": "north", "value": 27.1, "device": tpu}, "result"),
        ({"chunk": 128, "ok": True, "s": 3.2, "perms_per_sec": 590.1,
          "device": tpu}, "result"),
        ({"best": {"chunk": 128}, "device": tpu}, "result"),
        # drops: explicit markers
        ({"metric": "x", "error": "skipped", "tpu_fallback": True}, "dropped"),
        ({"metric": "backend probe", "warning": "falling back"}, "dropped"),
        # drops: tune resume replay — already transcribed once as the
        # original fresh row; each watcher rerun re-prints it
        ({"chunk": 128, "ok": True, "s": 3.2, "perms_per_sec": 590.1,
          "device": tpu, "cached": True}, "dropped"),
        # drops: failed tune point even on TPU (review r4: ok flag)
        ({"chunk": 128, "ok": False, "s": 1.0, "perms_per_sec": 9.9,
          "device": tpu}, "dropped"),
        # drops: CPU device — including the sweep's final best line
        ({"chunk": 128, "ok": True, "s": 3.2, "device": "TFRT_CPU_0"},
         "dropped"),
        ({"best": {"chunk": 128}, "device": "TFRT_CPU_0"}, "dropped"),
        ({"best": None, "device": tpu}, "dropped"),  # all points failed
        # unknown: anything without device attribution — value rows, best
        # lines, and drift tables alike (review r4: a device-less best/drift
        # row must never look clean or transcribe-ready)
        ({"chunk": 64, "ok": True, "s": 9.9, "perms_per_sec": 100.0},
         "unknown"),
        ({"best": {"chunk": 256, "perms_per_sec": 590}}, "unknown"),
        ({"metric": "bf16 drift", "per_stat": {"coherence": 0.47}},
         "unknown"),
        # other: device-attributed non-standard shape (bf16_drift table)
        ({"metric": "bf16 drift", "per_stat": {"coherence": 0.47},
          "device": tpu}, "other"),
        # serve observability rows (ISSUE 13): CPU by design, classified
        # BEFORE the CPU drop — cost table + top snapshot, never results
        ({"metric": "serve-cost per-tenant attributed [closed] (3 "
                    "tenants, chunk 32)", "value": 1.2, "unit": "device_s",
          "cost": {"alpha": {"device_s": 0.28, "perms": 256}},
          "device": "TFRT_CPU_0"}, "serve-cost"),
        ({"metric": "serve top snapshot", "value": 1, "unit": "snapshot",
          "top": {"tenants": [{"tenant": "drill", "burn_rate": 0.0}],
                  "brownout": False}}, "serve-top"),
        # fleet drill rows (ISSUE 14): the kill-failover load row and
        # the chaos --fleet verdict — robustness signals, CPU by design
        ({"metric": "serve-fleet 2 replicas kill-failover (9 req, "
                    "chunk 32)", "value": 5.3, "unit": "s",
          "failover_s": 0.25, "vs_1_replica": 2.0,
          "device": "TFRT_CPU_0"}, "serve-fleet"),
        ({"replicas": 2, "requests": 3, "killed_replica": "r0",
          "recovered": True, "bit_identical": True, "ok": True},
         "serve-fleet"),
        # autoscale / noticed-eviction rows (ISSUE 19): the square-wave
        # load row and the chaos --fleet --evict summary — their own
        # section, never folded into the kill-failover story
        ({"metric": "serve-autoscale square-wave min1/max3 (9 req, "
                    "2 evictions, chunk 32)", "value": 6.1, "unit": "s",
          "replica_seconds": 8.2, "replica_seconds_static": 67.3,
          "lost_requests": 0, "device": "TFRT_CPU_0"}, "serve-autoscale"),
        ({"replicas": 2, "requests": 3, "evicted_replica": "r1",
          "zero_recompute": True, "recovered": True,
          "bit_identical": True, "ok": True}, "serve-autoscale"),
        # warm-start proof rows (ISSUE 15): CPU by design, classified
        # into their own section — never a BASELINE measurement, and
        # never confused with the serve-fleet prefix
        ({"metric": "serve-warmstart fresh-process first-request "
                    "(100g/3m, chunk 32)", "value": 0.0031, "unit": "s",
          "cold_compile_span_s": 1.25, "warm_source": "aot",
          "warm_ok": True, "device": "TFRT_CPU_0"}, "serve-warmstart"),
    ]
    for row, want in cases:
        assert classify(row) == want, (row, classify(row), want)


def test_serve_cost_section_renders(tmp_path, capsys=None):
    rows = [
        {"metric": "serve-cost per-tenant attributed [closed] (3 tenants, "
                   "chunk 32)", "value": 1.2, "unit": "device_s",
         "cost": {"alpha": {"device_s": 0.28, "perms": 256,
                            "bytes_to_host": 43008, "requests": 3}},
         "device": "TFRT_CPU_0"},
        {"metric": "serve top snapshot", "value": 1, "unit": "snapshot",
         "top": {"tenants": [{"tenant": "drill", "burn_rate": 0.5}],
                 "brownout": False}},
    ]
    lines = summarize_watch.serve_cost_lines([rows[0]], [rows[1]])
    text = "\n".join(lines)
    assert "serve-cost per-tenant attributed" in text
    assert "alpha: device_s=0.28 perms=256" in text
    assert "brownout=False" in text and "drill=0.5" in text


def test_fleet_section_renders():
    """ISSUE 14: the fleet-drill section shows the newest kill-failover
    load row (failover time, vs-1-replica) and the newest chaos --fleet
    verdict."""
    rows = [
        {"metric": "serve-fleet 2 replicas kill-failover (9 req, "
                   "chunk 32)", "value": 5.3, "unit": "s",
         "p50_ms": 2100.0, "p99_ms": 3200.0, "failover_s": 0.25,
         "vs_1_replica": 2.01, "device": "TFRT_CPU_0"},
        {"replicas": 2, "requests": 3, "killed_replica": "r0",
         "recovered": True, "bit_identical": True, "ok": True},
    ]
    text = "\n".join(summarize_watch.fleet_lines(rows))
    assert "serve-fleet 2 replicas kill-failover" in text
    assert "failover=0.25s" in text and "vs_1_replica=2.01" in text
    assert "chaos --fleet PASSED" in text
    assert "killed=r0" in text and "bit_identical=True" in text


def test_autoscale_section_renders(tmp_path):
    """ISSUE 19: the autoscale section shows the newest square-wave load
    row (p99 vs the static peak fleet, replica-seconds saved, zero-lost
    gate) and the newest chaos --fleet --evict verdict — and an evicted
    summary never classifies into the serve-fleet kill section."""
    rows = [
        {"metric": "serve-autoscale square-wave min1/max3 (9 req, "
                   "2 evictions, chunk 32)", "value": 6.1, "unit": "s",
         "p99_ms": 2400.0, "p99_static_ms": 1900.0, "p99_within_2x": True,
         "replica_seconds": 8.2, "replica_seconds_static": 67.3,
         "replica_seconds_saved": 59.1, "lost_requests": 0,
         "evictions": 2, "device": "TFRT_CPU_0"},
        {"replicas": 2, "requests": 3, "evicted_replica": "r1",
         "zero_recompute": True, "recovered": True, "bit_identical": True,
         "ok": True},
    ]
    text = "\n".join(summarize_watch.autoscale_lines(rows))
    assert "serve-autoscale square-wave" in text
    assert "p99=2400.0ms vs static 1900.0ms" in text
    assert "within_2x=True" in text
    assert "replica_s=8.2 vs static 67.3 (saved=59.1)" in text
    assert "lost=0" in text and "evictions=2" in text
    assert "chaos --fleet --evict PASSED" in text
    assert "evicted=r1" in text and "zero_recompute=True" in text
    bad = "\n".join(summarize_watch.autoscale_lines(
        [{**rows[1], "ok": False, "zero_recompute": False}]))
    assert "FAILED" in bad and "zero_recompute=False" in bad

    log = tmp_path / "watch.jsonl"
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "autoscale drills (elastic-fleet + noticed-eviction health)" \
        in out
    # never folded into the kill-failover section
    assert "fleet drills (kill-failover health)" not in out


def test_warmstart_section_renders():
    """ISSUE 15: the warm-start section shows the newest proof row —
    warm vs cold compile span, source, verdict, and the delta vs the
    PR 14 coldstart baseline when a ledger history exists."""
    rows = [
        {"metric": "serve-warmstart fresh-process first-request "
                   "(100g/3m, chunk 32)", "value": 0.0031, "unit": "s",
         "cold_compile_span_s": 1.25, "warm_source": "aot",
         "coldstart_baseline_s": 0.9, "coldstart_delta_s": 0.8969,
         "warm_ok": True, "device": "TFRT_CPU_0"},
    ]
    text = "\n".join(summarize_watch.warmstart_lines(rows))
    assert "serve-warmstart fresh-process first-request" in text
    assert "warm compile_span 0.0031s (source=aot)" in text
    assert "vs cold 1.25s — OK" in text
    assert "baseline 0.9s" in text and "delta 0.8969s" in text

    rows[0]["warm_ok"] = False
    assert "FAILED" in "\n".join(summarize_watch.warmstart_lines(rows))


def test_cli_sections_account_for_every_parseable_row(tmp_path):
    rows = [
        {"metric": "north", "value": 27.1, "unit": "s", "vs_baseline": 2.21,
         "perms_per_sec": 368.5, "device": "TPU v5 lite"},
        {"metric": "Config D", "error": "skipped", "tpu_fallback": True},
        {"chunk": 256, "ok": True, "s": 5.2, "device": "TFRT_CPU_0"},
        {"chunk": 64, "ok": True, "s": 9.9, "perms_per_sec": 100.0},
        {"metric": "bf16 drift", "per_stat": {"coherence": 0.47},
         "device": "TPU v5 lite"},
    ]
    log = tmp_path / "watch.jsonl"
    log.write_text("--- step ---\n" + "\n".join(json.dumps(r) for r in rows))
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "north" in out and "27.1" in out            # clean row kept
    assert "unknown-provenance" in out and '"chunk": 64' in out
    assert "other parseable" in out and "bf16 drift" in out
    assert "TFRT_CPU_0" not in out                     # CPU row never shown
    assert "dropped 2" in proc.stderr                  # fallback + CPU


def _tel_event(ev, **data):
    return {"v": 1, "t": 1.0, "m": 1.0, "run": "r1", "ev": ev, "data": data}


def test_telemetry_rows_classified_and_split():
    """Telemetry event lines (netrep_tpu.utils.telemetry JSONL) classify
    as their own kind — never as unknown-provenance measurement rows —
    and aggregate into a per-phase time split."""
    ev = _tel_event("chunk", s=0.5, dispatches=2)
    assert classify(ev) == "telemetry"
    # near-misses stay on the old rules: wrong version / no data dict
    assert classify({"v": 2, "ev": "chunk", "data": {}}) == "unknown"
    assert classify({"v": 1, "ev": "chunk"}) == "unknown"
    split = summarize_watch.telemetry_split([
        _tel_event("chunk", s=0.5), _tel_event("chunk", s=1.5),
        _tel_event("observed", s=2.0),
        _tel_event("module_retired", module=3),   # no duration: excluded
    ])
    assert split == {"chunk": [2, 2.0], "observed": [1, 2.0]}


def test_cli_prints_telemetry_split(tmp_path):
    rows = [
        {"metric": "north", "value": 27.1, "unit": "s",
         "device": "TPU v5 lite"},
        _tel_event("superchunk", s=1.25, perms=512, dispatches=2),
        _tel_event("observed", s=0.75),
    ]
    log = tmp_path / "watch.jsonl"
    log.write_text("\n".join(json.dumps(r) for r in rows))
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "telemetry per-phase time split" in proc.stdout
    assert "superchunk: 1.250s" in proc.stdout
    assert "observed: 0.750s" in proc.stdout
    assert "north" in proc.stdout                     # result row intact


def test_lint_rows_classified_and_summarized(tmp_path):
    """Invariant-lint report lines (`python -m netrep_tpu lint --json`,
    appended once per watch cycle — ISSUE 12) classify as their own
    kind: never a measurement, never dropped as an error row even when
    non-ok, and summarized in a contract-health section."""
    clean = {"lint_v": 1, "ok": True, "files": 55, "rules": ["x"],
             "findings": [], "suppressed": [], "suppressions": [],
             "stale_suppressions": []}
    dirty = {**clean, "ok": False, "findings": [
        {"rule": "rng-discipline", "path": "a.py", "line": 3, "message": "m"},
        {"rule": "rng-discipline", "path": "b.py", "line": 9, "message": "m"},
        {"rule": "exception-taxonomy", "path": "c.py", "line": 1,
         "message": "m"},
    ]}
    assert classify(clean) == "lint"
    assert classify(dirty) == "lint"
    # near-miss: wrong schema version falls through to the old rules
    assert classify({"lint_v": 99, "findings": []}) != "lint"

    lines = summarize_watch.lint_lines([clean, dirty])
    assert "2 lint cycle(s): 1 clean, 1 with findings" in lines[0]
    assert "exception-taxonomy: 1" in lines[1]
    assert "rng-discipline: 2" in lines[1]

    log = tmp_path / "watch.jsonl"
    log.write_text(json.dumps(clean) + "\n" + json.dumps(dirty) + "\n")
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "invariant lint (contract health)" in proc.stdout


def _mixed_row(**over):
    row = {
        "metric": "mixed bf16-screened 96-perm null, 400 genes / 6 modules "
                  "(null_precision=bf16_rescue streaming vs f32, chunk 32)",
        "value": 0.394, "unit": "s", "vs_baseline": 1.8, "f32_s": 0.04,
        "mixed_vs_f32_x": 1.8, "rescued_fraction": 0.02,
        "counts_parity": True, "device": "TPU v5 lite",
    }
    row.update(over)
    return row


def test_mixed_rows_classified_and_rendered(tmp_path):
    """ISSUE 16: the CPU run of --config mixed is a deliberate
    parity/mechanism row (bf16 rounding emulated, vs_baseline nulled
    in-bench) — it must land in the screening-health section, never be
    silently dropped as a CPU row; a real TPU measurement still flows to
    the BASELINE result table."""
    cpu = _mixed_row(
        device="TFRT_CPU_0", vs_baseline=None, mixed_vs_f32_x=0.1,
        rescued_fraction=1.0,
        metric=_mixed_row()["metric"] + " [CPU emulated bf16 rounding: "
        "parity/mechanism row, reduced shape — the screen only pays off "
        "on MXU hardware]",
    )
    assert classify(cpu) == "mixed"
    # probe-race fallback variant keeps its mechanism value too
    assert classify(_mixed_row(tpu_fallback=True)) == "mixed"
    # a real TPU measurement is a BASELINE result, not a mechanism row
    assert classify(_mixed_row()) == "result"
    # near-miss: a mixed-prefixed row WITHOUT the screening fields is not
    # hijacked into the section (an ordinary CPU row still drops)
    assert classify({"metric": "mixed something", "value": 1.0,
                     "device": "TFRT_CPU_0"}) == "dropped"

    text = "\n".join(summarize_watch.mixed_lines([cpu]))
    assert "rescued_fraction=1.0" in text
    assert "vs f32 0.1x" in text and "(f32 0.04s)" in text
    assert "counts bit-identical" in text
    bad = "\n".join(summarize_watch.mixed_lines(
        [_mixed_row(counts_parity=False)]))
    assert "COUNTS PARITY FAILED" in bad

    log = tmp_path / "watch.jsonl"
    log.write_text(json.dumps(cpu) + "\n" + json.dumps(_mixed_row()) + "\n")
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "mixed-precision screening (bf16 fast-pass health)" in proc.stdout
    # the TPU row made the BASELINE table while the CPU row stayed in its
    # section — both visible, neither misattributed
    assert "BASELINE.md table snippet" in proc.stdout
    assert "TPU v5 lite" in proc.stdout


def _grid_row(**over):
    row = {
        "metric": "grid all-pairs atlas, 6 cohorts / 300 genes / 4 modules, "
                  "ceiling 96 perms (30 cells, adaptive, packed vs "
                  "sequential)",
        "value": 21.4, "unit": "s", "vs_baseline": 1.263,
        "sequential_s": 27.0, "perms_per_sec": 150.0,
        "grid_perms_evaluated": 3210, "sequential_perms_evaluated": 3210,
        "delta_s": 4.1, "delta_perms_evaluated": 579,
        "delta_perm_fraction": 0.1803, "cells": 30,
        "cells_reused_on_delta": 20, "cells_warmstarted_on_delta": 6,
        "dedup_hits": 25, "packs": 6, "bit_identical_to_solo": True,
        "device": "TPU v5 lite",
    }
    row.update(over)
    return row


def test_grid_rows_classified_and_rendered(tmp_path):
    """ISSUE 17: the CPU run of --config grid carries real mechanism
    verdicts (per-cell bit-parity vs solo and the <25% delta bound are
    asserted in-bench on any backend) — it must land in the atlas-health
    section, never be silently dropped as a CPU row; a real TPU
    measurement still flows to the BASELINE result table."""
    cpu = _grid_row(device="TFRT_CPU_0")
    assert classify(cpu) == "grid"
    # probe-race fallback variant keeps its mechanism value too
    assert classify(_grid_row(tpu_fallback=True)) == "grid"
    # a real TPU measurement is a BASELINE result, not a mechanism row
    assert classify(_grid_row()) == "result"
    # near-miss: a grid-prefixed row WITHOUT the parity marker is not
    # hijacked into the section (an ordinary CPU row still drops)
    assert classify({"metric": "grid something", "value": 1.0,
                     "device": "TFRT_CPU_0"}) == "dropped"

    text = "\n".join(summarize_watch.grid_lines([cpu]))
    assert "vs sequential 1.263x" in text and "(seq 27.0s)" in text
    assert "delta_perm_fraction=0.1803" in text
    assert "reused=20" in text and "warmstarted=6" in text
    assert "dedup_hits=25" in text and "packs=6" in text
    assert "cells bit-identical to solo" in text
    bad = "\n".join(summarize_watch.grid_lines(
        [_grid_row(bit_identical_to_solo=False)]))
    assert "CELL/SOLO PARITY FAILED" in bad

    log = tmp_path / "watch.jsonl"
    log.write_text(json.dumps(cpu) + "\n" + json.dumps(_grid_row()) + "\n")
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "all-pairs atlas (grid packing + delta re-analysis health)" \
        in proc.stdout
    # the TPU row made the BASELINE table while the CPU row stayed in its
    # section — both visible, neither misattributed
    assert "BASELINE.md table snippet" in proc.stdout
    assert "TPU v5 lite" in proc.stdout


def test_roofline_section_mechanism_vs_measurement(tmp_path):
    """ISSUE 18: roofline telemetry events get their own section, with
    the safety-critical split — a CPU/no-peak-entry row (utilisation
    null) is a MECHANISM check of the cost accounting, never a TPU
    measurement; only utilisation-bearing rows read as the measured
    roofline story."""
    measured = _tel_event(
        "roofline", family="mxu", device_kind="tpu v4", utilisation=0.31,
        achieved_pps=5100.0, sol_pps=16400.0, flops_per_perm=1898752,
        bytes_per_perm=45056, flops=10, bytes_hbm=4,
        peak_flops=275e12, peak_bw=1228e9,
    )
    mech = _tel_event(
        "roofline", family="direct", device_kind="cpu", utilisation=None,
        achieved_pps=800.0, sol_pps=None, flops_per_perm=1898752,
        bytes_per_perm=45056, flops=10, bytes_hbm=4,
        peak_flops=None, peak_bw=None,
    )
    lines = summarize_watch.roofline_lines([measured, mech])
    m_line = [ln for ln in lines if ln.startswith("mxu")][0]
    c_line = [ln for ln in lines if ln.startswith("direct")][0]
    assert "utilisation 0.31 of speed of light" in m_line
    assert "MECHANISM" not in m_line
    assert "MECHANISM row" in c_line
    assert "never transcribe as a TPU measurement" in c_line
    # both classify as telemetry (never unknown-provenance measurements)
    assert classify(measured) == "telemetry"
    # end-to-end: the section renders above the per-phase split
    log = tmp_path / "watch.jsonl"
    log.write_text(json.dumps(measured) + "\n" + json.dumps(mech) + "\n")
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "## roofline (achieved vs speed of light, 2 run(s))" \
        in proc.stdout


def test_anomalies_section_groups_by_detector(tmp_path):
    """ISSUE 20: ``anomaly_detected`` telemetry events get their own
    triage section — per pinned detector, the firing count and the newest
    occurrence's detail — and classify as telemetry (never as
    unknown-provenance measurement rows)."""
    events = [
        _tel_event("anomaly_detected", detector="device_lost",
                   start=32, take=16, error="InjectedDeviceLost"),
        _tel_event("anomaly_detected", detector="device_lost",
                   start=48, take=16, error="InjectedDeviceLost"),
        _tel_event("anomaly_detected", detector="slo_burn",
                   tenant="acme", burn_rate=2.5),
    ]
    for e in events:
        assert classify(e) == "telemetry"
    lines = summarize_watch.anomaly_lines(events)
    dl = [ln for ln in lines if ln.startswith("device_lost")][0]
    burn = [ln for ln in lines if ln.startswith("slo_burn")][0]
    assert "fired x2" in dl and "start=48" in dl     # newest detail wins
    assert "fired x1" in burn and "tenant=acme" in burn
    # end-to-end: the section renders, count visible, above the split
    log = tmp_path / "watch.jsonl"
    log.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    proc = subprocess.run(
        [sys.executable, "benchmarks/summarize_watch.py", str(log)],
        cwd=REPO, capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "## anomalies (3 detector firing(s)" in proc.stdout
    assert "device_lost: fired x2" in proc.stdout
