"""``python -m netrep_tpu`` — the deployment CLI must run the selftest,
honor flags, and exit nonzero on failure so scripts and CI can gate on it.

One compiled selftest subprocess serves every assertion here (VERDICT r5
weak #3: this module used to pay four subprocess runs, two of them full
selftest compiles — the shared module-scoped run below halves the compile
cost and still covers both the JSON surface and the dead-tunnel fallback,
because it runs under the hostile env where both behaviors matter at
once). Subprocesses share the suite's persistent compile cache via
``JAX_COMPILATION_CACHE_DIR`` (they don't load conftest, and a cold
selftest compile is ~2 min on this 1-core box)."""

import json
import os
import subprocess
import sys

import pytest

from netrep_tpu.utils.backend import host_cpu_fingerprint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {
    **os.environ,
    # the image's sitecustomize re-pins JAX_PLATFORMS=axon at interpreter
    # startup, so the CLI's resolve_backend_or_cpu() does the real work;
    # a short probe budget keeps the dead-tunnel fallback fast in CI
    "JAX_PLATFORMS": "cpu",
    "NETREP_BACKEND_PROBE_TIMEOUT": "10",
    "JAX_COMPILATION_CACHE_DIR": os.path.join(
        REPO, ".jax_cache", host_cpu_fingerprint()
    ),
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0.5",
}


def _run(*args, timeout=420, env=ENV):
    return subprocess.run(
        [sys.executable, "-m", "netrep_tpu", *args],
        cwd=REPO, env=env, timeout=timeout, capture_output=True, text=True,
    )


@pytest.fixture(scope="module")
def selftest_proc():
    """The ONE selftest subprocess (the module's only compiled run), under
    the driver's hostile env: axon plugin pinned and the tunnel dead — so
    the same run proves the JSON output surface AND the round-2 rc=124
    failure mode (CLI must fall back to CPU within the probe budget
    instead of hanging; same pattern as test_graft_entry)."""
    axon_site = "/root/.axon_site"
    env = {
        **ENV,
        "JAX_PLATFORMS": "axon",
        "NETREP_BACKEND_PROBE_TIMEOUT": "20",
    }
    if os.path.isdir(axon_site) and axon_site not in env.get("PYTHONPATH", ""):
        env["PYTHONPATH"] = (
            axon_site + os.pathsep + env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
    return _run("selftest", "--n-perm", "8", "--max-shapes", "1", "--json",
                env=env)


def test_version():
    proc = _run("version")
    assert proc.returncode == 0
    import netrep_tpu

    assert proc.stdout.strip() == netrep_tpu.__version__


def test_selftest_json_single_shape(selftest_proc):
    assert selftest_proc.returncode == 0, selftest_proc.stderr[-3000:]
    row = json.loads(selftest_proc.stdout.strip().splitlines()[-1])
    assert row["ok"] and row["n_shapes"] == 1
    # max_shapes=1 must gate on the LARGEST validated shape (VERDICT r5
    # weak #5): the small shape alone can hide shape-dependent miscompiles
    from netrep_tpu.utils.selftest import _SHAPES

    assert row["shape_nodes"] == [max(n for _, n, _ in _SHAPES)]


def test_bad_max_shapes_fails_fast_at_argparse():
    import time

    t0 = time.perf_counter()
    proc = _run("selftest", "--n-perm", "8", "--max-shapes", "0")
    took = time.perf_counter() - t0
    assert proc.returncode == 2  # argparse usage error
    assert "must be >= 1" in proc.stderr
    # usage errors must not pay the backend probe budget (review r5)
    assert took < 30, took


def test_cli_hang_safe_under_dead_tunnel(selftest_proc):
    """The CLI's distinguishing behavior: the shared run above executed
    with the axon plugin pinned and the tunnel dead — completing at all
    (returncode 0, valid JSON on a CPU device) IS the hang-safety proof."""
    assert selftest_proc.returncode == 0, selftest_proc.stderr[-3000:]
    row = json.loads(selftest_proc.stdout.strip().splitlines()[-1])
    assert row["ok"]
    assert "cpu" in row["backend"].lower() or "cpu" in row["device"].lower()


def test_chaos_drill_cli(tmp_path):
    """``python -m netrep_tpu chaos`` (ISSUE 6): the one-line elastic
    drill — injected partial loss + capacity restore on a virtual
    4-device mesh — recovers, proves bit-parity, prints the recovery
    timeline, and exits 0. The exact command tpu_watch.sh runs per
    cycle."""
    tel = str(tmp_path / "chaos.jsonl")
    env = {
        **ENV,
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "NETREP_FAULT_PLAN": "device_lost_partial@24;capacity_restored@40",
    }
    proc = _run("chaos", "--telemetry", tel, "--json", env=env)
    assert proc.returncode == 0, proc.stderr[-3000:]
    summary = json.loads(proc.stdout.strip().splitlines()[-1])
    assert summary["ok"] and summary["recovered"] and summary["bit_identical"]
    evs = [json.loads(l)["ev"] for l in open(tel)]
    assert "mesh_shrunk" in evs and "mesh_grown" in evs
    assert "degraded_to_cpu" not in evs  # survivors existed


def test_chaos_drill_cli_fails_loudly_on_unrecovered(tmp_path):
    """A fatal-fault plan cannot be recovered from — the drill must exit
    nonzero (the watch loop logs it as a ladder regression) rather than
    report success."""
    proc = _run("chaos", "--plan", "fatal@24", "--devices", "1", "--json")
    assert proc.returncode != 0
