"""Persistent per-(backend, bucket-caps, chunk) autotune cache
(utils/autotune.py): record/reuse semantics, corruption tolerance, and the
engine integration — the null loop must record measured steady-state
throughput and the next engine build with the same problem shape must
reuse the best-measured perm batch instead of the byte-budget heuristic.
Tuning never changes values: the default path stays bit-identical.
"""

import json
import os

import numpy as np
import pytest

from netrep_tpu.utils import autotune
from netrep_tpu.utils.autotune import AutotuneCache, make_key, resolve_perm_batch
from netrep_tpu.utils.config import EngineConfig


def test_record_and_best_setting(tmp_path):
    cache = AutotuneCache(str(tmp_path / "at.json"))
    key = make_key("cpu", "direct", "32x2", 64)
    assert cache.best_setting(key) is None
    cache.record(key, 8, 100.0)
    cache.record(key, 16, 300.0)
    cache.record(key, 16, 200.0)
    assert cache.best_setting(key) == 16
    # median beats a single lucky sample: three slow measurements for 32
    # with one outlier must not overtake 16's median
    cache.record(key, 32, 9000.0)
    cache.record(key, 32, 50.0)
    cache.record(key, 32, 60.0)
    assert cache.best_setting(key) == 16
    assert cache.throughput(key, 16) == [300.0, 200.0]


def test_sample_window_bounded(tmp_path):
    cache = AutotuneCache(str(tmp_path / "at.json"))
    key = make_key("cpu", "direct", "32x1", 64)
    for i in range(20):
        cache.record(key, 4, float(i + 1))
    assert len(cache.throughput(key, 4)) == autotune._KEEP


def test_corrupt_or_foreign_file_treated_as_empty(tmp_path):
    path = str(tmp_path / "at.json")
    with open(path, "w") as f:
        f.write("{not json")
    cache = AutotuneCache(path)
    assert cache.best_setting("anything") is None
    cache.record("k", 2, 10.0)  # recovers by rewriting
    assert cache.best_setting("k") == 2
    with open(path, "w") as f:
        json.dump({"format": 999, "entries": {"k": {"2": [1.0]}}}, f)
    assert AutotuneCache(path).best_setting("k") is None


def test_resolve_perm_batch_contract(tmp_path, monkeypatch):
    monkeypatch.setattr(autotune, "default_path",
                        lambda: str(tmp_path / "at.json"))
    key = make_key("cpu", "mxu", "64x3", 128)
    # autotune off: heuristic, nothing recorded
    pb, cache = resolve_perm_batch(EngineConfig(autotune=False), key, 4)
    assert pb == 4 and cache is None
    # autotune on, empty cache: heuristic, but a recording handle
    pb, cache = resolve_perm_batch(EngineConfig(), key, 4)
    assert pb == 4 and cache is not None
    # a better-measured setting overrides the heuristic
    cache.record(key, 4, 50.0)
    cache.record(key, 12, 400.0)
    pb, _ = resolve_perm_batch(EngineConfig(), key, 4)
    assert pb == 12
    # an explicit perm_batch is honored (rides in as the resolved value)
    # while keeping the recording handle so sweeps feed the cache
    pb, cache = resolve_perm_batch(EngineConfig(perm_batch=2), key, 2)
    assert pb == 2 and cache is not None


@pytest.fixture
def toy_engine_parts():
    from netrep_tpu.parallel.engine import ModuleSpec

    rng = np.random.default_rng(0)
    n, s = 96, 24
    x = rng.standard_normal((s, n)).astype(np.float32)
    c = np.corrcoef(x, rowvar=False).astype(np.float32)
    np.fill_diagonal(c, 1.0)
    net = (np.abs(c) ** 2).astype(np.float32)
    specs = [
        ModuleSpec("1", np.arange(0, 12, dtype=np.int32),
                   np.arange(0, 12, dtype=np.int32)),
        ModuleSpec("2", np.arange(12, 30, dtype=np.int32),
                   np.arange(12, 30, dtype=np.int32)),
    ]
    pool = np.arange(n, dtype=np.int32)
    return (c, net, x), specs, pool


def _build(parts, config):
    from netrep_tpu.parallel.engine import PermutationEngine

    (c, net, x), specs, pool = parts
    return PermutationEngine(c, net, x, c, net, x, specs, pool,
                             config=config)


def test_engine_records_and_reuses_measured_throughput(
    toy_engine_parts, tmp_path, monkeypatch
):
    monkeypatch.setattr(autotune, "default_path",
                        lambda: str(tmp_path / "at.json"))
    cfg = EngineConfig(chunk_size=16, summary_method="eigh")
    eng = _build(toy_engine_parts, cfg)
    eng.run_null(64, key=0)  # 4 chunks: enough steady-state marks
    assert eng._autotune_record is not None
    cache, key, pb = eng._autotune_record
    assert key == eng.autotune_key()
    samples = cache.throughput(key, pb)
    assert samples and all(v > 0 for v in samples)
    # a (synthetic) better setting recorded for the SAME key is what the
    # next engine build resolves — the heuristic is no longer re-derived
    cache.record(key, 7, samples[0] * 1000)
    eng2 = _build(toy_engine_parts, cfg)
    eng2.chunk_body()
    assert eng2._autotune_record[2] == 7


def test_autotune_empty_cache_is_bit_identical(toy_engine_parts, tmp_path,
                                               monkeypatch):
    """With nothing measured yet the heuristic runs unchanged — the
    default path stays bit-identical to a run with autotune disabled."""
    monkeypatch.setattr(autotune, "default_path",
                        lambda: str(tmp_path / "at.json"))
    base_cfg = EngineConfig(chunk_size=16, summary_method="eigh",
                            autotune=False)
    nulls_off, done = _build(toy_engine_parts, base_cfg).run_null(48, key=1)
    nulls_on, done_on = _build(
        toy_engine_parts, EngineConfig(chunk_size=16, summary_method="eigh")
    ).run_null(48, key=1)
    assert done == done_on
    np.testing.assert_array_equal(np.asarray(nulls_off),
                                  np.asarray(nulls_on))


def test_autotuned_batch_drifts_only_at_float_rounding(toy_engine_parts,
                                                       tmp_path,
                                                       monkeypatch):
    """Reusing a measured batch re-partitions lax.map — accumulation-order
    drift at f32 rounding level only (the docstring's honest claim)."""
    monkeypatch.setattr(autotune, "default_path",
                        lambda: str(tmp_path / "at.json"))
    base_cfg = EngineConfig(chunk_size=16, summary_method="eigh",
                            autotune=False)
    nulls_off, _ = _build(toy_engine_parts, base_cfg).run_null(48, key=1)
    eng = _build(toy_engine_parts, EngineConfig(chunk_size=16,
                                                summary_method="eigh"))
    AutotuneCache().record(eng.autotune_key(), 3, 1e9)
    nulls_on, _ = eng.run_null(48, key=1)
    assert eng._autotune_record[2] == 3
    np.testing.assert_allclose(np.asarray(nulls_off), np.asarray(nulls_on),
                               rtol=2e-6, atol=2e-7)


def test_unwritable_cache_dir_is_nonfatal(tmp_path):
    blocked = tmp_path / "blocked"
    blocked.mkdir()
    os.chmod(blocked, 0o500)
    try:
        cache = AutotuneCache(str(blocked / "sub" / "at.json"))
        cache.record("k", 2, 10.0)  # must not raise, whatever happens
        # root ignores the mode bits, so the write may have succeeded —
        # only the no-crash behavior is the contract here
        assert cache.best_setting("k") in (None, 2)
    finally:
        os.chmod(blocked, 0o700)
