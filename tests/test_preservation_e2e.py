"""End-to-end test of `module_preservation` on vignette-like toy data — the
rebuild of the reference's de-facto integration test (SURVEY.md §2.1
"Vignette", §4; Config A in BASELINE.md): planted preserved modules must come
out significant, and the API surface (validation, result shaping,
alternatives, data-less variant) behaves like the reference's.
"""

import logging

import numpy as np
import pytest

from netrep_tpu import module_preservation
from netrep_tpu.models.results import PreservationResult
from netrep_tpu.ops.oracle import STAT_NAMES, TOPOLOGY_STATS
from netrep_tpu.utils.config import EngineConfig

try:
    import pandas as pd
except Exception:
    pd = None

CFG = EngineConfig(chunk_size=64, summary_method="power", power_iters=50)


# the shared 250-perm `result` fixture lives in conftest.py
# (session-scoped: one engine pass serves every API-surface test; its
# kwargs — n_perm=250, seed=123, chunk 64, power summary — are what the
# assertions below pin). The pandas packaging helper is a package import
# (ADVICE r5: `from conftest import ...` relies on pytest's prepend import
# mode and dies under importmode=importlib).
from netrep_tpu.data import pair_frames as _frames  # noqa: E402


def test_simplified_single_pair(result):
    assert isinstance(result, PreservationResult)
    assert result.discovery == "disc" and result.test == "test"
    assert result.completed == 250
    assert result.observed.shape == (4, 7)
    assert result.nulls.shape == (250, 4, 7)
    assert result.p_values.shape == (4, 7)


def test_planted_modules_are_preserved(result):
    """All 4 planted modules are strongly preserved: every statistic
    significant at the resolution of 250 permutations."""
    assert (result.max_pvalue() < 0.05).all()
    # p-values can never be zero (Phipson–Smyth)
    assert (result.p_values > 0).all()


def test_overlap_bookkeeping(result, toy_pair_module):
    sizes = toy_pair_module["module_sizes"]
    assert list(result.total_size) == [sizes[l] for l in result.module_labels]
    assert (result.n_vars_present <= result.total_size).all()
    assert (result.prop_vars_present <= 1.0).all()
    assert (result.n_vars_present >= 2).all()


def test_repr_and_frames(result):
    text = repr(result)
    assert "disc" in text and "p-values" in text
    assert list(result.p_frame().columns) == list(STAT_NAMES)


def test_no_simplify_nesting(toy_pair_module):
    d, t = _frames(toy_pair_module)
    res = module_preservation(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=toy_pair_module["labels"],
        discovery="disc", test="test",
        n_perm=10, seed=0, simplify=False, config=CFG,
    )
    assert set(res) == {"disc"} and set(res["disc"]) == {"test"}


def test_dataless_end_to_end(toy_pair_module):
    d, t = _frames(toy_pair_module)
    res = module_preservation(
        network={"disc": d["network"], "test": t["network"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=toy_pair_module["labels"],
        discovery="disc", test="test",
        n_perm=50, seed=1, config=CFG,
    )
    topo = [STAT_NAMES.index(s) for s in TOPOLOGY_STATS]
    other = [i for i in range(7) if i not in topo]
    assert np.isfinite(res.p_values[:, topo]).all()
    assert np.isnan(res.p_values[:, other]).all()


def test_alternative_less_flips(toy_pair_module):
    d, t = _frames(toy_pair_module)
    kw = dict(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=toy_pair_module["labels"],
        discovery="disc", test="test", n_perm=100, seed=5, config=CFG,
    )
    hi = module_preservation(alternative="greater", **kw)
    lo = module_preservation(alternative="less", **kw)
    # strongly preserved modules: greater-p small, less-p near 1
    assert hi.p_values[:, 0].max() < 0.1
    assert lo.p_values[:, 0].min() > 0.9


def test_validation_errors(toy_pair_module):
    d, t = _frames(toy_pair_module)
    bad_net = t["network"].copy()
    bad_net.iloc[0, 1] = 2.0  # breaks symmetry
    with pytest.raises(ValueError, match="not symmetric"):
        module_preservation(
            network={"disc": d["network"], "test": bad_net},
            correlation={"disc": d["correlation"], "test": t["correlation"]},
            module_assignments=toy_pair_module["labels"],
            discovery="disc", test="test", n_perm=5,
        )
    with pytest.raises(ValueError, match="correlation must be provided"):
        module_preservation(
            network={"disc": d["network"], "test": t["network"]},
            module_assignments=toy_pair_module["labels"],
            discovery="disc", test="test", n_perm=5,
        )
    with pytest.raises(ValueError, match="not found"):
        module_preservation(
            network={"disc": d["network"], "test": t["network"]},
            correlation={"disc": d["correlation"], "test": t["correlation"]},
            module_assignments=toy_pair_module["labels"],
            discovery="nope", test="test", n_perm=5,
        )
    with pytest.raises(ValueError, match="alternative"):
        module_preservation(
            network={"disc": d["network"], "test": t["network"]},
            correlation={"disc": d["correlation"], "test": t["correlation"]},
            module_assignments=toy_pair_module["labels"],
            discovery="disc", test="test", n_perm=5, alternative="sideways",
        )


def test_network_from_correlation_user_surface(toy_pair_module):
    """module_preservation with EngineConfig(network_from_correlation=2.0):
    the toy fixture's networks are |corr|**2, so results equal the default
    run while the engine never puts the n x n networks on device."""
    d, t = _frames(toy_pair_module)
    kwargs = dict(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=dict(toy_pair_module["labels"]),
        discovery="disc", test="test", n_perm=40, seed=11,
    )
    base = module_preservation(
        **kwargs, config=EngineConfig(chunk_size=16, summary_method="eigh")
    )
    derived = module_preservation(
        **kwargs,
        config=EngineConfig(chunk_size=16, summary_method="eigh",
                            network_from_correlation=2.0),
    )
    np.testing.assert_allclose(derived.observed, base.observed,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(derived.nulls, base.nulls, rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(derived.p_values, base.p_values)


def test_all_tpu_knobs_compose_end_to_end():
    """Kitchen-sink integration: every TPU tuning knob at once — fused
    Pallas gather (interpret on CPU) with forced hi/lo exact selection,
    derived network, multiple-of-8 bucket capacities — must reproduce the
    default path's null through the PUBLIC API. Guards knob interactions
    no single-feature test crosses. Uses a 38-node module so the
    granularity knob actually changes bucket padding (the toy fixture's
    <= 15-node modules round identically under g=8 and g=32)."""
    assert (EngineConfig().rounded_cap(38)
            != EngineConfig(cap_granularity=8).rounded_cap(38))
    rng = np.random.default_rng(23)
    n, s = 110, 30
    names = [f"g{i}" for i in range(n)]

    def build(seed):
        r = np.random.default_rng(seed)
        x = r.standard_normal((s, n))
        x[:, :38] += r.standard_normal((s, 1)) * 1.3   # planted 38-node mod
        x[:, 38:47] += r.standard_normal((s, 1)) * 1.1  # planted 9-node mod
        df = pd.DataFrame(x, columns=names)
        corr = df.corr().to_numpy()
        return dict(
            data=df,
            correlation=pd.DataFrame(corr, index=names, columns=names),
            network=pd.DataFrame(np.abs(corr) ** 2, index=names,
                                 columns=names),
        )

    d, t = build(1), build(2)
    assign = {nm: ("1" if i < 38 else "2" if i < 47 else "0")
              for i, nm in enumerate(names)}
    kwargs = dict(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=assign,
        discovery="disc", test="test", n_perm=40, seed=19,
    )
    base = module_preservation(
        **kwargs, config=EngineConfig(chunk_size=16, summary_method="eigh")
    )
    stacked = module_preservation(
        **kwargs,
        config=EngineConfig(
            chunk_size=16, summary_method="eigh", gather_mode="fused",
            fused_exact="always", network_from_correlation=2.0,
            cap_granularity=8,
        ),
    )
    np.testing.assert_allclose(stacked.observed, base.observed,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(stacked.nulls, base.nulls,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(stacked.p_values, base.p_values)


def test_result_save_load_roundtrip(result, tmp_path):
    """PreservationResult.save/load: the .rds-saving workflow equivalent."""
    path = str(tmp_path / "res.npz")
    result.save(path)
    back = PreservationResult.load(path)
    assert back.discovery == result.discovery and back.test == result.test
    assert back.module_labels == result.module_labels
    assert back.alternative == result.alternative
    assert back.n_perm == result.n_perm and back.completed == result.completed
    np.testing.assert_array_equal(back.observed, result.observed)
    np.testing.assert_array_equal(back.nulls, result.nulls)
    np.testing.assert_array_equal(back.p_values, result.p_values)
    np.testing.assert_array_equal(back.total_size, result.total_size)
    # derived views still work on the loaded object
    np.testing.assert_array_equal(back.max_pvalue(), result.max_pvalue())
    assert repr(back) == repr(result)
    # foreign .npz (e.g. a null checkpoint) → informative error, not KeyError
    import numpy as _np

    foreign = str(tmp_path / "foreign.npz")
    with open(foreign, "wb") as fh:
        _np.savez(fh, nulls=_np.zeros(3))
    with pytest.raises(ValueError, match="not a PreservationResult"):
        PreservationResult.load(foreign)
    # future version → version error
    bad = str(tmp_path / "bad.npz")
    with open(bad, "wb") as fh:
        _np.savez(fh, result_version=_np.int64(99))
    with pytest.raises(ValueError, match="version"):
        PreservationResult.load(bad)
