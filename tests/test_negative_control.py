"""Negative-control end-to-end test: the framework's scientific job is not
just calling planted modules preserved (tests/test_preservation_e2e.py) but
NOT calling modules that aren't there (the reference's motivating use case —
replication failure). Two controls:

- a module planted in the discovery dataset whose test dataset is pure
  noise (structure lost — the classic non-replicating module), and
- a "module" of random unstructured nodes in both datasets.

Under the null, each of the 7 statistics' p-values is ~uniform, so a
module's max-p over 7 statistics is < 0.2 with probability 0.2^7 ≈ 1e-5 —
the assertions below are deterministic-seed-safe.
"""

import numpy as np
import pandas as pd

from netrep_tpu import module_preservation
from netrep_tpu.utils.config import EngineConfig


def _coexpr(rng, n, s, planted=()):
    """Noise data with planted co-expressed blocks. Each plant is
    ``(lo, hi, loadings)`` — per-node factor loadings must be HETEROGENEOUS
    and shared across datasets for the module to have a reproducible
    correlation *structure* (equal loadings make within-module correlations
    constant, leaving cor.cor/cor.contrib nothing but noise to concord on)."""
    x = rng.standard_normal((s, n))
    for lo, hi, loadings in planted:
        x[:, lo:hi] += rng.standard_normal(s)[:, None] * loadings[None, :]
    z = x - x.mean(0)
    z /= np.linalg.norm(z, axis=0)
    corr = np.clip(z.T @ z, -1, 1)
    return x, corr, np.abs(corr) ** 2


def test_unreplicated_and_random_modules_not_called():
    rng = np.random.default_rng(11)
    n, s = 90, 60
    names = [f"g{i}" for i in range(n)]
    # discovery: module "1" planted on nodes 0:15, module "2" is 15:30 but
    # will NOT be planted in test; module "3" is a random unstructured set
    load1 = rng.uniform(0.6, 2.2, 15)   # shared across datasets → replicates
    load2 = rng.uniform(0.6, 2.2, 15)   # discovery-only → lost in test
    d_x, d_corr, d_net = _coexpr(rng, n, s,
                                 planted=[(0, 15, load1), (15, 30, load2)])
    t_x, t_corr, t_net = _coexpr(rng, n, s, planted=[(0, 15, load1)])

    labels = {}
    rand_nodes = rng.choice(np.arange(30, n), size=12, replace=False)
    for i, nm in enumerate(names):
        if i < 15:
            labels[nm] = "1"
        elif i < 30:
            labels[nm] = "2"
        elif i in rand_nodes:
            labels[nm] = "3"
        else:
            labels[nm] = "0"

    df = lambda m: pd.DataFrame(m, index=names, columns=names)
    res = module_preservation(
        network={"d": df(d_net), "t": df(t_net)},
        data={"d": pd.DataFrame(d_x, columns=names),
              "t": pd.DataFrame(t_x, columns=names)},
        correlation={"d": df(d_corr), "t": df(t_corr)},
        module_assignments=labels,
        discovery="d", test="t", n_perm=400, seed=5,
        config=EngineConfig(chunk_size=64, summary_method="power",
                            power_iters=50),
    )
    by = dict(zip(res.module_labels, res.max_pvalue()))
    # the replicated module is called; the lost and random ones are not
    assert by["1"] < 0.05, by
    assert by["2"] > 0.2, by
    assert by["3"] > 0.2, by
    assert res.preserved_modules(adjust="none") == ["1"]


def test_null_pvalues_not_extreme_on_pure_noise():
    """All-noise datasets with arbitrary module labels: no module×statistic
    p-value may be at the permutation floor (a floor hit on noise means the
    null distribution is mis-sampled or statistics leak the observed set)."""
    rng = np.random.default_rng(23)
    n, s, n_perm = 80, 30, 300
    names = [f"g{i}" for i in range(n)]
    d_x, d_corr, d_net = _coexpr(rng, n, s)
    t_x, t_corr, t_net = _coexpr(rng, n, s)
    labels = {nm: str(1 + i % 3) if i < 45 else "0"
              for i, nm in enumerate(names)}
    df = lambda m: pd.DataFrame(m, index=names, columns=names)
    res = module_preservation(
        network={"d": df(d_net), "t": df(t_net)},
        data={"d": pd.DataFrame(d_x, columns=names),
              "t": pd.DataFrame(t_x, columns=names)},
        correlation={"d": df(d_corr), "t": df(t_corr)},
        module_assignments=labels,
        discovery="d", test="t", n_perm=n_perm, seed=9,
        config=EngineConfig(chunk_size=64, summary_method="power",
                            power_iters=50),
    )
    floor = 1.0 / (n_perm + 1)
    assert np.nanmin(res.p_values) > floor + 1e-12, res.p_frame()
    assert res.preserved_modules() == []
