"""Parity tests for the fused (Pallas) gather path — the round-3 roofline
lever (BASELINE.md roofline: the hot loop is bandwidth-bound on the row
gather; the fused kernel does one HBM pass per row set instead of the XLA
path's several). On CPU the kernel runs in the Pallas interpreter; the
engine contract is that 'fused' computes the SAME null as 'direct' given
the same seed (selection is exact 0/1 arithmetic in f32 on CPU).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from netrep_tpu.ops.fused_gather import gather_submatrix_fused
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig


def _problem(rng, n_disc=90, n_test=80, n_samples=12,
             sizes=(7, 9, 34)):  # crosses one bucket boundary
    def build(n):
        x = rng.standard_normal((n_samples, n))
        c = np.corrcoef(x, rowvar=False)
        return x, c, np.abs(c) ** 2

    d = build(n_disc)
    t = build(n_test)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    pool = np.arange(n_test, dtype=np.int32)
    return d, t, specs, pool


def test_kernel_matches_advanced_indexing(rng):
    n = 300
    M = rng.standard_normal((n, n)).astype(np.float32)
    idx = rng.integers(0, n, size=(4, 5, 24)).astype(np.int32)
    out = np.asarray(
        gather_submatrix_fused(jnp.asarray(M), jnp.asarray(idx), interpret=True)
    )
    ref = M[idx[..., :, None], idx[..., None, :]]
    np.testing.assert_array_equal(out, ref)


def test_kernel_sentinel_rows_and_columns_zero(rng):
    n = 150
    M = rng.standard_normal((n, n)).astype(np.float32)
    idx = rng.integers(0, n, size=(2, 16)).astype(np.int32)
    idx[:, -3:] = n  # sentinel padding
    out = np.asarray(
        gather_submatrix_fused(jnp.asarray(M), jnp.asarray(idx), interpret=True)
    )
    ref = M[idx[..., :, None].clip(0, n - 1), idx[..., None, :].clip(0, n - 1)]
    ref[..., :, -3:] = 0.0  # sentinel columns zero out
    ref[..., -3:, :] = 0.0  # sentinel rows are un-owned -> zero too
    np.testing.assert_array_equal(out, ref)


def test_kernel_bf16_storage_selects_bit_true(rng):
    # bf16 storage + fused kernel: stored values must be selected exactly
    # (one-hot dot of bf16 values with f32 accumulate loses nothing) — the
    # precision contract for the dtype='bfloat16' engine mode
    n = 256
    M = rng.standard_normal((n, n)).astype(np.float32)
    M16 = jnp.asarray(M, jnp.bfloat16)
    idx = rng.integers(0, n, size=(4, 24)).astype(np.int32)
    out = np.asarray(gather_submatrix_fused(M16, jnp.asarray(idx), interpret=True))
    ref = np.asarray(M16)[idx[..., :, None], idx[..., None, :]].astype(np.float32)
    np.testing.assert_array_equal(out, ref)


def test_kernel_exact_mode_hilo(rng):
    # hi/lo split must reproduce values to f32 precision even though both
    # dots run in bf16 (the CPU interpreter uses f32 dots, so this also
    # pins that the split arithmetic itself is lossless-composable)
    n = 200
    M = (rng.standard_normal((n, n)) * 100).astype(np.float32)
    idx = rng.integers(0, n, size=(3, 32)).astype(np.int32)
    out = np.asarray(gather_submatrix_fused(
        jnp.asarray(M), jnp.asarray(idx), interpret=True, exact=True
    ))
    ref = M[idx[..., :, None], idx[..., None, :]]
    # bf16(hi) + bf16(residual) reconstructs f32 to ~2^-16 relative
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-4)


def test_row_block_selection_at_real_scales():
    # pure arithmetic — pins the VMEM guard's behavior at the gene counts
    # users actually hit, against the default 8 MiB budget
    from netrep_tpu.ops.fused_gather import _COL_TILE, _ROW_BLOCK, _row_block

    # n=20k f32: ceil(20000/512)*512*4 = 80 KiB/row; 128 rows = 10 MiB > 8
    # (ADVICE r3 flagged the full block as a Mosaic-compile risk) -> 96
    # fits, but two steps are needed either way, so the minimal-padding
    # block for 2 steps is 64 (rpad == cap, zero padded select FLOPs)
    assert _row_block(128, 20_000, 4) == 64
    assert _row_block(128, 20_000, 2) == 128   # bf16 halves the row bytes
    assert _row_block(160, 20_000, 4) == 80    # 2 steps, zero pad (not 96)
    assert _row_block(96, 100_000, 4) == 16    # review r4: halving gave 8
    assert _row_block(128, 30_000, 4) == 64    # ADVICE r3's failing case
    assert _row_block(128, 100_000, 4) == 16
    assert _row_block(24, 600, 4) == 24        # small problems untouched
    # alignment: every guarded result is a multiple of 8 (or == cap < 8)
    for n in (20_000, 50_000, 100_000, 250_000):
        rb = _row_block(128, n, 4)
        assert rb % 8 == 0 and 8 <= rb <= _ROW_BLOCK, (n, rb)
    with np.testing.assert_raises_regex(ValueError, "gather_mode='mxu'"):
        _row_block(128, 3_000_000, 4)          # 8 rows still ~93 MiB
    assert _COL_TILE % 128 == 0                # lane alignment invariant


def test_kernel_vmem_guard_downscales_row_block(rng, monkeypatch):
    # a small VMEM budget must shrink the row block (ADVICE r3: large n
    # would otherwise exceed VMEM and fail Mosaic compilation) without
    # changing results
    from netrep_tpu.ops import fused_gather

    n = 600  # 2 col tiles -> 4 KiB/row in f32
    monkeypatch.setattr(fused_gather, "_VMEM_BUDGET", 64 * 1024)  # rb -> 16
    fused_gather._run.clear_cache()
    try:
        M = rng.standard_normal((n, n)).astype(np.float32)
        idx = rng.integers(0, n, size=(3, 64)).astype(np.int32)
        out = np.asarray(gather_submatrix_fused(
            jnp.asarray(M), jnp.asarray(idx), interpret=True
        ))
        np.testing.assert_array_equal(
            out, M[idx[..., :, None], idx[..., None, :]]
        )
    finally:
        fused_gather._run.clear_cache()  # drop traces built under the
        # patched budget so later tests retrace with the real one


def test_kernel_vmem_guard_raises_at_minimum_block(rng, monkeypatch):
    from netrep_tpu.ops import fused_gather

    n = 600
    monkeypatch.setattr(fused_gather, "_VMEM_BUDGET", 1000)  # < 8 rows
    fused_gather._run.clear_cache()
    try:
        M = rng.standard_normal((n, n)).astype(np.float32)
        idx = rng.integers(0, n, size=(2, 24)).astype(np.int32)
        with np.testing.assert_raises_regex(ValueError, "gather_mode='mxu'"):
            gather_submatrix_fused(
                jnp.asarray(M), jnp.asarray(idx), interpret=True
            )
    finally:
        fused_gather._run.clear_cache()


def test_fused_exact_typo_rejected():
    # any string other than 'always' must raise, not silently act as True
    # (code review r4): 'Always' on a CPU CI runner would otherwise skip
    # the very coverage the mode exists for
    with np.testing.assert_raises_regex(ValueError, "fused_exact"):
        EngineConfig(fused_exact="Always")


def test_fused_exact_always_runs_hilo_on_cpu(rng):
    # fused_exact='always' forces the hi/lo split through the ENGINE path
    # in interpret mode (VERDICT r3 weak #3: the plain fused_exact=True
    # config is gated off on CPU, so without this the split's first real
    # execution would be on a TPU mid-benchmark)
    from netrep_tpu.parallel.engine import make_fused_gather

    assert make_fused_gather(
        EngineConfig(gather_mode="fused", fused_exact="always")
    ).keywords["exact"] is True
    assert make_fused_gather(
        EngineConfig(gather_mode="fused", fused_exact=True)
    ).keywords["exact"] is False  # CPU gate unchanged for the bool form

    d, t, specs, pool = _problem(rng)
    eng = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=8, gather_mode="fused",
                            fused_exact="always", power_iters=30),
    )
    ref = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=8, gather_mode="direct",
                            power_iters=30),
    )
    out, _ = eng.run_null(8, key=2)
    exp, _ = ref.run_null(8, key=2)
    # hi/lo reconstruction is ~2^-16-relative; statistics attenuate further
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)


def test_kernel_shape_fuzz_matches_advanced_indexing(rng):
    # randomized shapes across the kernel's decision space: row-block
    # boundaries (cap vs _ROW_BLOCK), column-tile spill (n vs _COL_TILE),
    # sentinel density, batch dims — every draw must reproduce plain
    # advanced indexing exactly in f32 interpret mode
    for draw in range(6):
        n = int(rng.integers(40, 1300))
        cap = int(rng.integers(2, 150))
        batch = tuple(rng.integers(1, 4, size=int(rng.integers(1, 3))))
        M = rng.standard_normal((n, n)).astype(np.float32)
        idx = rng.integers(0, n, size=(*batch, cap)).astype(np.int32)
        n_sent = int(rng.integers(0, cap // 2 + 1))
        if n_sent:
            flat = idx.reshape(-1, cap)
            for r in range(flat.shape[0]):  # sentinels at random slots
                flat[r, rng.choice(cap, size=n_sent, replace=False)] = n
        out = np.asarray(gather_submatrix_fused(
            jnp.asarray(M), jnp.asarray(idx), interpret=True
        ))
        ref = M[idx[..., :, None].clip(0, n - 1),
                idx[..., None, :].clip(0, n - 1)]
        ref[np.broadcast_to((idx == n)[..., :, None], ref.shape)] = 0.0
        ref[np.broadcast_to((idx == n)[..., None, :], ref.shape)] = 0.0
        np.testing.assert_array_equal(
            out, ref, err_msg=f"draw {draw}: n={n} cap={cap} batch={batch}"
        )


def test_fused_null_matches_direct(rng):
    d, t, specs, pool = _problem(rng)
    nulls = {}
    for mode in ("direct", "fused"):
        eng = PermutationEngine(
            d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
            config=EngineConfig(
                chunk_size=8, gather_mode=mode, summary_method="power",
                power_iters=30,
            ),
        )
        out, done = eng.run_null(16, key=7)
        assert done == 16
        nulls[mode] = out
    # same seed => same permutations; CPU f32 selection exact on both paths
    np.testing.assert_allclose(
        nulls["fused"], nulls["direct"], rtol=1e-5, atol=1e-6
    )


def test_fused_null_derived_network_and_chunk_invariance(rng):
    d, t, specs, pool = _problem(rng)
    cfgs = [
        EngineConfig(chunk_size=c, gather_mode="fused",
                     network_from_correlation=2.0, power_iters=30)
        for c in (4, 16)
    ]
    outs = []
    for cfg in cfgs:
        eng = PermutationEngine(
            d[1], d[2], d[0], t[1], t[2], t[0], specs, pool, config=cfg
        )
        out, _ = eng.run_null(16, key=3)
        outs.append(out)
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)
    assert np.isfinite(outs[0]).all()


def test_fused_exact_config_matches_direct(rng):
    d, t, specs, pool = _problem(rng)
    eng = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=8, gather_mode="fused",
                            fused_exact=True, power_iters=30),
    )
    ref = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=8, gather_mode="direct",
                            power_iters=30),
    )
    out, _ = eng.run_null(8, key=2)
    exp, _ = ref.run_null(8, key=2)
    # hi/lo reconstruction is ~2^-16-relative; statistics attenuate further
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)


def test_fused_prime_chunk_pads_batches(rng):
    # chunk 7 with perm_batch 4: Cp=8, one padded permutation computed and
    # dropped — results must still match the direct path exactly
    d, t, specs, pool = _problem(rng)
    eng = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=7, gather_mode="fused",
                            perm_batch=4, power_iters=30),
    )
    ref = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=7, gather_mode="direct",
                            power_iters=30),
    )
    out, done = eng.run_null(14, key=9)
    exp, _ = ref.run_null(14, key=9)
    assert done == 14
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)


def test_multitest_fused_matches_default(rng):
    # Config C + fused kernel: same seed => same nulls as the default
    # (direct-gather) multi-test path, both cohorts
    from netrep_tpu.parallel.multitest import MultiTestEngine

    d, t, specs, pool = _problem(rng)
    t2_data = t[0] + rng.standard_normal(t[0].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2
    args = (
        d[1], d[2], d[0],
        np.stack([t[1], t2_corr]),
        np.stack([t[2], t2_net]),
        [t[0], t2_data],
        specs, pool,
    )
    nulls = {}
    for mode in ("direct", "fused"):
        eng = MultiTestEngine(
            *args,
            config=EngineConfig(chunk_size=6, gather_mode=mode,
                                summary_method="eigh"),
        )
        out, done = eng.run_null(10, key=5)
        assert done == 10 and out.shape[0] == 2
        nulls[mode] = out
    np.testing.assert_allclose(
        nulls["fused"], nulls["direct"], rtol=1e-5, atol=2e-5
    )


def test_multitest_fused_resolves_batch_against_real_chunk(rng, monkeypatch):
    """ADVICE r3: the fused multi-test path once passed a 1<<30 sentinel as
    the chunk to resolved_perm_batch, silently skipping the clamp of an
    explicit perm_batch. Null VALUES cannot discriminate (batching only
    changes scheduling), so pin the resolution call itself: the chunk
    argument must be the engine's real effective chunk."""
    from netrep_tpu.parallel.multitest import MultiTestEngine

    d, t, specs, pool = _problem(rng)
    args = (
        d[1], d[2], d[0],
        np.stack([t[1]]), np.stack([t[2]]), [t[0]],
        specs, pool,
    )
    seen = []
    orig = EngineConfig.resolved_perm_batch

    def spy(self, gather_mode, platform, chunk, bytes_per_perm=None):
        seen.append((gather_mode, chunk))
        return orig(self, gather_mode, platform, chunk, bytes_per_perm)

    monkeypatch.setattr(EngineConfig, "resolved_perm_batch", spy)
    eng = MultiTestEngine(
        *args,
        config=EngineConfig(chunk_size=6, gather_mode="fused",
                            summary_method="eigh", perm_batch=64),
    )
    out, done = eng.run_null(8, key=5)
    assert done == 8
    fused_calls = [c for gm, c in seen if gm == "fused"]
    assert fused_calls, "fused path never resolved a perm batch"
    for chunk in fused_calls:
        assert chunk == eng._base.effective_chunk() == 6, (
            f"fused multi-test resolved perm_batch against chunk={chunk}, "
            "not the engine's real effective chunk"
        )


def test_fused_perm_mesh_replicated_matches_unmeshed(rng):
    # replicated matrices + perm-axis mesh: the fused chunk runs under
    # shard_map (XLA cannot auto-partition a pallas_call); same key =>
    # same null as the unmeshed fused engine (mesh-invariance contract)
    from netrep_tpu.parallel.mesh import make_mesh

    d, t, specs, pool = _problem(rng)
    n_dev = len(jax.devices("cpu"))
    mesh = make_mesh(n_perm_shards=n_dev, n_row_shards=1)
    eng = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=2 * n_dev, gather_mode="fused",
                            power_iters=30),
        mesh=mesh,
    )
    ref = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=8, gather_mode="fused",
                            power_iters=30),
    )
    n_perm = 2 * eng.effective_chunk()
    out, done = eng.run_null(n_perm, key=17)
    exp, _ = ref.run_null(n_perm, key=17)
    assert done == n_perm
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_multitest_fused_perm_mesh_matches_unmeshed(rng):
    # multi-test + fused + perm-axis mesh: chunk runs under shard_map —
    # previously this combination silently ran single-device
    from netrep_tpu.parallel.mesh import make_mesh
    from netrep_tpu.parallel.multitest import MultiTestEngine

    d, t, specs, pool = _problem(rng)
    t2_data = t[0] + rng.standard_normal(t[0].shape) * 0.5
    t2_corr = np.corrcoef(t2_data, rowvar=False)
    t2_net = np.abs(t2_corr) ** 2
    args = (
        d[1], d[2], d[0],
        np.stack([t[1], t2_corr]),
        np.stack([t[2], t2_net]),
        [t[0], t2_data],
        specs, pool,
    )
    n_dev = len(jax.devices("cpu"))
    mesh = make_mesh(n_perm_shards=n_dev, n_row_shards=1)
    eng = MultiTestEngine(
        *args,
        config=EngineConfig(chunk_size=n_dev, gather_mode="fused",
                            power_iters=30),
        mesh=mesh,
    )
    ref = MultiTestEngine(
        *args,
        config=EngineConfig(chunk_size=4, gather_mode="fused",
                            power_iters=30),
    )
    n_perm = 2 * eng._base.effective_chunk()
    out, done = eng.run_null(n_perm, key=23)
    exp, _ = ref.run_null(n_perm, key=23)
    assert done == n_perm
    np.testing.assert_allclose(out, exp, rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_fused_row_sharded_matches_replicated(rng):
    # Config D composition: row-sharded matrices + fused per-shard kernel
    # (psum-assembled) must equal the replicated direct path with the same
    # seed — exercised on the virtual 8-device CPU mesh in interpret mode
    from netrep_tpu.parallel.mesh import make_mesh

    d, t, specs, pool = _problem(rng)
    n_dev = len(jax.devices("cpu"))
    n_row = 2
    mesh = make_mesh(n_perm_shards=n_dev // n_row, n_row_shards=n_row)
    eng = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(
            chunk_size=2 * (n_dev // n_row), gather_mode="fused",
            matrix_sharding="row", power_iters=30,
        ),
        mesh=mesh,
    )
    ref = PermutationEngine(
        d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
        config=EngineConfig(chunk_size=8, gather_mode="direct",
                            power_iters=30),
    )
    n_perm = 2 * eng.effective_chunk()
    out, done = eng.run_null(n_perm, key=11)
    exp, _ = ref.run_null(n_perm, key=11)
    assert done == n_perm
    np.testing.assert_allclose(out, exp, rtol=1e-4, atol=1e-5)
    # observed pass through the fused row-sharded gatherer
    np.testing.assert_allclose(
        eng.observed(), ref.observed(), rtol=1e-4, atol=1e-5
    )
