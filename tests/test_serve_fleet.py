"""Fleet-serving tests (ISSUE 14) — CPU-only, in-process, tiny
fixtures: hash-ring placement stability, journal-ship round-trip
(including a torn final segment and offset resume), replica-kill
failover with counts/p-values/adaptive decisions BIT-IDENTICAL to an
undisturbed run (via the shipped journal + the SHARED checkpoint
directory), idempotency dedup across failover (zero recompute),
fleet-wide brownout admission from the aggregate backlog estimate,
``SocketClient`` redirect-hint following, the fleet-labeled cold-start
perf-ledger entry, and the per-replica ``top``/``telemetry``
sections."""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from netrep_tpu import module_preservation
from netrep_tpu.data import make_mixed_pair
from netrep_tpu.serve import (
    FleetConfig, HashRing, InProcessClient, PreservationServer, QueueFull,
    ServeConfig, build_inprocess_fleet,
)
from netrep_tpu.serve import journal as jnl
from netrep_tpu.serve.journal import JournalShipper
from netrep_tpu.utils.config import EngineConfig, FaultPolicy

#: the ONE engine config fleet-served runs and their direct twins share
CFG = EngineConfig(chunk_size=16, autotune=False)


@pytest.fixture(scope="module")
def fx():
    mixed = make_mixed_pair(100, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    direct_kw = dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", config=CFG,
    )
    return dict(dn=dn, dc=dc, dd=dd, tn=tn, tc=tc, td=td, assign=assign,
                direct_kw=direct_kw)


def direct(fx, **kw):
    return module_preservation(**fx["direct_kw"], **kw)


def read_events(path):
    return [json.loads(l) for l in open(path, encoding="utf-8")]


def make_fleet(fx, tmp_path, n=2, *, register=True, tel="coord",
               heartbeat_s=0.1, fleet_config_kw=None, start_servers=True,
               replica_tel=True):
    """N-replica in-process fleet over the shared fixture pair, each
    replica journaled + telemetry'd into ``tmp_path``."""
    fc = FleetConfig(telemetry=str(tmp_path / f"{tel}.jsonl"),
                     heartbeat_s=heartbeat_s,
                     **(fleet_config_kw or {}))

    def mk(rid, jpath, ckpt):
        return ServeConfig(
            engine=CFG, journal=jpath, checkpoint_dir=ckpt,
            checkpoint_every=16, fleet_label=rid,
            telemetry=(str(tmp_path / f"{rid}_tel.jsonl")
                       if replica_tel else None),
        )

    fleet = build_inprocess_fleet(
        n, str(tmp_path / "fleet"), make_config=mk, fleet_config=fc,
        start_servers=start_servers,
    )
    if register:
        fleet.register_dataset("a", "d", network=fx["dn"],
                               correlation=fx["dc"], data=fx["dd"],
                               assignments=fx["assign"])
        fleet.register_dataset("a", "t", network=fx["tn"],
                               correlation=fx["tc"], data=fx["td"])
    return fleet


# ---------------------------------------------------------------------------
# hash ring
# ---------------------------------------------------------------------------

def test_hash_ring_stability_on_leave_and_join():
    """The consistent-hashing contract: removing a replica remaps ONLY
    the keys it owned; adding it back restores the exact original
    placement. Placement is deterministic (no RNG)."""
    ring = HashRing(vnodes=64)
    for rid in ("r0", "r1", "r2"):
        ring.add(rid)
    keys = [f"digest-{i}" for i in range(1000)]
    before = {k: ring.route(k) for k in keys}
    assert set(before.values()) == {"r0", "r1", "r2"}  # all replicas used
    ring.remove("r1")
    after = {k: ring.route(k) for k in keys}
    for k in keys:
        if before[k] != "r1":
            assert after[k] == before[k], "a surviving replica's key moved"
        else:
            assert after[k] in ("r0", "r2")
    ring.add("r1")
    assert {k: ring.route(k) for k in keys} == before  # exact restore
    # determinism: a fresh ring with the same members places identically
    ring2 = HashRing(vnodes=64)
    for rid in ("r0", "r1", "r2"):
        ring2.add(rid)
    assert {k: ring2.route(k) for k in keys} == before


def test_hash_ring_successor_is_a_distinct_live_peer():
    ring = HashRing(vnodes=8)
    ring.add("r0")
    assert ring.successor("r0") is None          # nobody else to ship to
    ring.add("r1")
    assert ring.successor("r0") == "r1"
    assert ring.successor("r1") == "r0"
    assert ring.route("anything") in ("r0", "r1")


# ---------------------------------------------------------------------------
# journal shipping
# ---------------------------------------------------------------------------

def test_journal_ship_round_trip_with_torn_segment(tmp_path):
    """The shipped copy is a valid journal: complete lines only, the
    torn in-flight tail waits for its completion, the acked offset
    persists across a shipper restart (re-ship never skips, never
    duplicates)."""
    src = str(tmp_path / "src.jsonl")
    dst = str(tmp_path / "ship" / "src_copy.jsonl")
    j = jnl.RequestJournal(src)
    j.append("tenant", tenant="a", weight=1)
    j.append("accepted", seq=1, id="r1", key="k1", tenant="a",
             discovery="d", test="t", params={"n_perm": 64, "seed": 3})
    shipper = JournalShipper(src, dst, replica="r0")
    assert shipper.flush() > 0
    # a torn in-flight line: NOT shipped until its newline lands
    with open(src, "a", encoding="utf-8") as f:
        f.write('{"jv": 1, "kind": "done", "seq": 1, "key": "k1"')
        f.flush()
    assert shipper.flush() == 0
    state = jnl.scan(dst)
    assert [r["key"] for r in state["pending"]] == ["k1"]
    assert not state["results"]
    # the line completes; a FRESH shipper resumes from the persisted
    # offset and ships exactly the remainder
    with open(src, "a", encoding="utf-8") as f:
        f.write(', "result": {"p": 1}}\n')
    resumed = JournalShipper(src, dst, replica="r0")
    assert resumed.acked_offset == shipper.acked_offset
    assert resumed.flush() > 0
    state = jnl.scan(dst)
    assert list(state["results"]) == ["k1"] and not state["pending"]
    # byte-identical copy (the shipped journal IS the journal)
    assert open(dst, "rb").read() == open(src, "rb").read()
    j.close()


def test_journal_shipper_emits_shipped_event(tmp_path):
    from netrep_tpu.utils.telemetry import Telemetry

    src = str(tmp_path / "src.jsonl")
    tel_path = str(tmp_path / "tel.jsonl")
    j = jnl.RequestJournal(src)
    j.append("tenant", tenant="a", weight=1)
    j.close()
    tel = Telemetry(tel_path)
    shipper = JournalShipper(src, str(tmp_path / "dst.jsonl"),
                             replica="r7", telemetry=tel)
    assert shipper.flush() > 0
    tel.close()
    ev = [e for e in read_events(tel_path)
          if e["ev"] == "journal_shipped"]
    assert ev and ev[0]["data"]["replica"] == "r7"
    assert ev[0]["data"]["records"] == 1
    assert ev[0]["data"]["bytes"] > 0


# ---------------------------------------------------------------------------
# routing + parity (no faults)
# ---------------------------------------------------------------------------

def test_fleet_routes_deterministically_and_serves_bit_identical(
        fx, tmp_path):
    fleet = make_fleet(fx, tmp_path)
    try:
        home = fleet.route("a", "d", "t")
        assert home is fleet.route("a", "d", "t")   # stable placement
        res = fleet.analyze("a", "d", "t", n_perm=32, seed=3,
                            timeout=600)
        res2 = fleet.analyze("a", "d", "t", n_perm=32, seed=3,
                             timeout=600)
        st = fleet.stats()
    finally:
        fleet.close()
    d = direct(fx, n_perm=32, seed=3)
    np.testing.assert_array_equal(res["p_values"], np.asarray(d.p_values))
    np.testing.assert_array_equal(res2["p_values"], res["p_values"])
    # locality: both requests ran on the SAME replica (warm pool)
    served_on = [rid for rid, row in st["replicas"].items()
                 if row.get("packs")]
    assert served_on == [home.rid]
    # every live replica row carries the roofline gauge (ISSUE 18) —
    # None on device kinds without a peak-table entry, never absent
    assert all("utilisation" in row for row in st["replicas"].values())
    # the top dashboard renders the per-replica section from these stats
    from netrep_tpu.serve.top import render, snapshot

    snap = snapshot(st)
    assert snap["fleet"] and len(snap["replicas"]) == 2
    assert {r["replica"] for r in snap["replicas"]} == {"r0", "r1"}
    frame = render(snap)
    assert "replica" in frame and "r0" in frame and "fleet" in frame


# ---------------------------------------------------------------------------
# replica-kill failover (the tentpole acceptance)
# ---------------------------------------------------------------------------

def test_replica_kill_failover_bit_parity(fx, tmp_path):
    """Mid-pack replica death (the in-process SIGKILL stand-in) → the
    health loop fails the shipped journal over to the peer → every
    request completes with counts/p-values/adaptive decisions
    bit-identical to direct calls (= an undisturbed single-replica run,
    by the PR 7 parity pin), the partial pack RESUMING from the shared
    checkpoint directory rather than restarting."""
    fleet = make_fleet(fx, tmp_path)
    submits = [
        ("k1", dict(n_perm=64, seed=3)),
        ("k2", dict(n_perm=64, seed=5)),
        ("k3", dict(n_perm=32, seed=11, adaptive=True)),
    ]
    try:
        home = fleet.route("a", "d", "t")
        peer_rid = [r for r in ("r0", "r1") if r != home.rid][0]
        home.arm_fault_plan(FaultPolicy(plan="crash@24",
                                        backoff_base_s=0.0,
                                        backoff_jitter=0.0))
        results = {}
        errors = []

        def worker(k, kw):
            try:
                results[k] = fleet.analyze("a", "d", "t",
                                           idempotency_key=k,
                                           timeout=600, **kw)
            except Exception as e:   # surfaced after join
                errors.append(f"{k}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=s, daemon=True)
                   for s in submits]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not errors, errors
        st = fleet.stats()
    finally:
        fleet.close()
    # dead rows now also carry the lifecycle state + generation (ISSUE 19)
    dead_row = st["replicas"][home.rid]
    assert dead_row["alive"] is False
    assert dead_row["state"] == "dead" and dead_row["gen"] == 0
    assert st["replicas"][peer_rid]["done"] == 3
    for k, kw in submits:
        d = direct(fx, **kw)
        np.testing.assert_array_equal(results[k]["observed"], d.observed)
        np.testing.assert_array_equal(results[k]["p_values"],
                                      np.asarray(d.p_values))
        if kw.get("adaptive"):
            np.testing.assert_array_equal(results[k]["n_perm_used"],
                                          np.asarray(d.n_perm_used))
    # the coordinator's event story: lost → failover pair (with the
    # measured time) → ring rebalance, all labeled with the replica
    ev = read_events(str(tmp_path / "coord.jsonl"))
    fo = [e for e in ev if e["ev"] in
          ("replica_lost", "failover_start", "failover_done",
           "ring_rebalanced") and e["data"].get("reason") != "join"]
    assert [e["ev"] for e in fo] == [
        "replica_lost", "failover_start", "failover_done",
        "ring_rebalanced",
    ]
    done = fo[2]["data"]
    assert done["replica"] == home.rid and done["peer"] == peer_rid
    assert done["s"] > 0 and done["requeued"] == 3
    # the peer ADOPTED (journal_replayed) and RESUMED the partial pack
    # from the shared checkpoint dir — recovery started mid-run
    pe = read_events(str(tmp_path / f"{peer_rid}_tel.jsonl"))
    replay = [e for e in pe if e["ev"] == "journal_replayed"]
    assert replay and replay[0]["data"]["adopted"] is True
    assert replay[0]["data"]["requeued"] == 3
    resumed = [e for e in pe if e["ev"] == "checkpoint_resumed"]
    assert resumed and resumed[0]["data"]["completed"] >= 16
    # the fleet events render in the --recovery timeline (failover time
    # included) and in the per-replica telemetry section
    from netrep_tpu.utils.telemetry import render_recovery, render_replicas

    timeline = render_recovery(str(tmp_path / "coord.jsonl"))
    assert "failover_done" in timeline and "replica_lost" in timeline
    section = render_replicas(str(tmp_path / "coord.jsonl"))
    assert home.rid in section and "failover" in section


def test_dedup_across_failover_never_recomputes(fx, tmp_path):
    """A request COMPLETED before its replica died is answered from the
    shipped journal on the peer — same numbers, zero packs dispatched on
    the peer (the one-computation-per-idempotency-key contract crosses
    the failover boundary)."""
    fleet = make_fleet(fx, tmp_path)
    try:
        home = fleet.route("a", "d", "t")
        peer_rid = [r for r in ("r0", "r1") if r != home.rid][0]
        r1 = fleet.analyze("a", "d", "t", n_perm=32, seed=3,
                           idempotency_key="K", timeout=600)
        # the replica dies AFTER completing (clean worker exit is as
        # dead as a SIGKILL to the health loop); the final ship pass
        # carries its `done` record to the copy
        home.server.close(drain=True)
        assert fleet.await_failover(home.rid, timeout=60)
        r2 = fleet.analyze("a", "d", "t", n_perm=32, seed=3,
                           idempotency_key="K", timeout=60)
        st = fleet.stats()
    finally:
        fleet.close()
    np.testing.assert_array_equal(np.asarray(r1["p_values"]),
                                  np.asarray(r2["p_values"]))
    np.testing.assert_array_equal(np.asarray(r1["counts_hi"]),
                                  np.asarray(r2["counts_hi"]))
    assert st["replicas"][peer_rid]["packs"] == 0   # pure journal answer
    assert st["tenants"]["a"]["deduped"] >= 1


# ---------------------------------------------------------------------------
# fleet-wide admission
# ---------------------------------------------------------------------------

def test_fleet_admission_sheds_from_aggregate_estimate(fx, tmp_path):
    """Brownout goes fleet-wide: the shed decision reads the AGGREGATE
    backlog (summed across replicas) over the summed rate estimates —
    and answers with the honest drain-time hint."""
    # heartbeat LONG: the workers deliberately never start, and the
    # health loop must not declare them lost mid-test on a slow machine
    # (this test is about the admission math, not liveness)
    fleet = make_fleet(
        fx, tmp_path, start_servers=False, heartbeat_s=30.0,
        fleet_config_kw=dict(brownout_enter_s=1.0, rate_pps=10.0),
    )
    try:
        # backlog forms on the HOME replica only (workers never start);
        # the estimate is still fleet-wide: 128 perms / (2 x 10 pps)
        home = fleet.route("a", "d", "t")
        for i in range(2):
            home.server.submit("a", "d", "t", n_perm=64, seed=i)
        est = fleet.drain_estimate()
        assert est == pytest.approx(128 / 20.0)
        with pytest.raises(QueueFull) as exc:
            fleet.analyze("a", "d", "t", n_perm=64, seed=9, timeout=5)
        assert exc.value.retry_after_s is not None
        assert exc.value.retry_after_s > 0
    finally:
        fleet.close(drain=False)
    ev = read_events(str(tmp_path / "coord.jsonl"))
    enter = [e for e in ev if e["ev"] == "serve_brownout_enter"]
    assert enter and enter[0]["data"]["fleet"] is True
    assert enter[0]["data"]["est_drain_s"] > 1.0


# ---------------------------------------------------------------------------
# SocketClient redirect hints (satellite)
# ---------------------------------------------------------------------------

def _fake_daemon(path, respond, received):
    """One-shot line-JSON unix-socket server for client-behavior tests."""
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(path)
    listener.listen(4)

    def serve():
        while True:
            try:
                conn, _ = listener.accept()
            except OSError:
                return
            with conn:
                f = conn.makefile("r", encoding="utf-8")
                while True:
                    line = f.readline()
                    if not line:
                        break
                    op = json.loads(line)
                    received.append(op)
                    resp = respond(op)
                    conn.sendall(
                        (json.dumps(resp) + "\n").encode("utf-8"))

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return listener


def test_socket_client_follows_redirect_under_one_key(tmp_path):
    """The coordinator's ``redirect`` hint (``--fleet-route redirect``)
    re-points the client at the named replica socket and re-sends the
    SAME op immediately — same idempotency key, same trace id, no retry
    attempt consumed."""
    from netrep_tpu.serve.client import SocketClient

    coord_path = str(tmp_path / "coord.sock")
    replica_path = str(tmp_path / "replica.sock")
    seen_coord, seen_replica = [], []
    l1 = _fake_daemon(
        coord_path,
        lambda op: {"ok": False, "retryable": True,
                    "redirect": replica_path},
        seen_coord,
    )
    l2 = _fake_daemon(
        replica_path,
        lambda op: {"ok": True,
                    "result": {"p_values": [0.5], "completed": 4}},
        seen_replica,
    )
    try:
        client = SocketClient(coord_path, timeout=30)
        res = client.analyze("a", "d", "t", n_perm=4, seed=1, retries=0)
        assert res["completed"] == 4
        assert client.path == replica_path    # future ops go direct
        client.close()
    finally:
        l1.close()
        l2.close()
    assert len(seen_coord) == 1 and len(seen_replica) == 1
    # the redirected re-send is the SAME logical request
    assert (seen_replica[0]["idempotency_key"]
            == seen_coord[0]["idempotency_key"])
    assert (seen_replica[0]["trace_ctx"]["trace"]
            == seen_coord[0]["trace_ctx"]["trace"])


# ---------------------------------------------------------------------------
# cold-start perf-ledger fingerprint (satellite)
# ---------------------------------------------------------------------------

def test_fleet_replica_records_coldstart_ledger_entry(fx, tmp_path,
                                                      monkeypatch):
    """A fleet-labeled replica's FIRST completed pack lands a
    ``serve-fleet-coldstart|<rid>|...`` perf-ledger entry carrying the
    measured compile span — the baseline the AOT warm-start goal
    (ROADMAP item 1) has to beat. One entry per replica boot; the
    second pack records nothing new."""
    from netrep_tpu.utils import perfledger

    ledger = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("NETREP_PERF_LEDGER", ledger)
    fleet = make_fleet(fx, tmp_path)
    try:
        fleet.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
        fleet.analyze("a", "d", "t", n_perm=32, seed=4, timeout=600)
    finally:
        fleet.close()
    cold = [e for e in perfledger.read_entries(ledger)
            if e["fingerprint"].startswith("serve-fleet-coldstart|")]
    assert len(cold) == 1
    e = cold[0]
    assert e["mode"] == "fleet-coldstart" and e["source"] == "serve"
    assert e["fingerprint"].split("|")[1] in ("r0", "r1")
    assert e["compile_s"] is not None and e["compile_s"] >= 0
    assert e["perms_per_sec"] > 0
    assert e["metric"].startswith("serve-fleet coldstart")


def test_standalone_server_records_no_coldstart(fx, tmp_path, monkeypatch):
    from netrep_tpu.utils import perfledger

    ledger = str(tmp_path / "ledger.jsonl")
    monkeypatch.setenv("NETREP_PERF_LEDGER", ledger)
    srv = PreservationServer(ServeConfig(engine=CFG))
    client = InProcessClient(srv)
    client.register_dataset("a", "d", network=fx["dn"],
                            correlation=fx["dc"], data=fx["dd"],
                            assignments=fx["assign"])
    client.register_dataset("a", "t", network=fx["tn"],
                            correlation=fx["tc"], data=fx["td"])
    try:
        client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
    finally:
        srv.close()
    entries = (perfledger.read_entries(ledger)
               if os.path.exists(ledger) else [])
    assert not [e for e in entries
                if e["fingerprint"].startswith("serve-fleet-coldstart")]
