"""Atlas tiled network plane (ISSUE 9): the construction pass over the
tile grid (top-k / τ selection vs a dense reference, interrupt → resume
round-trip through the ``x_atlas_*`` checkpoint extras, mesh-sharded
bit-parity, telemetry span tree, autotuned tile edge) and the data-only
module plane (``module_preservation(data_only=…)`` parity against the
dense path on materialized ``|corr|**β`` matrices — counts bit-identical
on CPU — plus the SparseAdjacency bridge onto the Config E engine)."""

import json
import warnings

import numpy as np
import pytest

import jax

import netrep_tpu
from netrep_tpu.atlas import (
    TiledNetwork, build_sparse_network, derived_net_np,
)
from netrep_tpu.atlas.modules import dense_reference_stats
from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.ops.sparse import SparseAdjacency
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.parallel.mesh import make_mesh
from netrep_tpu.utils.config import EngineConfig

CFG = EngineConfig(autotune=False)
BETA = 2.0


@pytest.fixture(scope="module")
def atlas_data():
    """Structured data with planted modules, ragged vs the tile edge the
    tests use (n=300, edge=64 → a 44-column tail tile)."""
    rng = np.random.default_rng(3)
    x = rng.standard_normal((20, 300))
    for k in range(4):
        x[:, k * 22:(k + 1) * 22] += 1.2 * rng.standard_normal(20)[:, None]
    return x


def dense_reference(x, beta=BETA):
    """(corr, net) the tile plane derives, materialized the dense way."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r = np.corrcoef(x, rowvar=False)
    np.fill_diagonal(r, 0.0)
    return r, derived_net_np(r, beta)


def test_topk_construction_matches_dense_reference(atlas_data):
    x = atlas_data
    n, k = x.shape[1], 6
    build = build_sparse_network(
        TiledNetwork.from_data(x, BETA), top_k=k, tile_edge=64, config=CFG
    )
    r, net = dense_reference(x)
    rows, cols, vals = [], [], []
    for i in range(n):
        order = np.argsort(-np.abs(r[i]), kind="stable")[:k]
        rows += [i] * k
        cols += list(order)
        vals += list(net[i, order])
    ref = SparseAdjacency.from_coo(rows, cols, vals, n, symmetrize=True)
    d_got, d_ref = build.adjacency.to_dense(), ref.to_dense()
    assert ((d_got != 0) == (d_ref != 0)).all()
    np.testing.assert_allclose(d_got, d_ref, atol=1e-6)
    # the degree vector covers the FULL derived network, not just kept edges
    np.testing.assert_allclose(build.degree, net.sum(axis=1), atol=1e-5)
    assert build.n == n and build.selected_edges == n * k


def test_tau_construction_matches_dense_reference(atlas_data):
    x = atlas_data
    n, tau = x.shape[1], 0.45
    build = build_sparse_network(
        TiledNetwork.from_data(x, BETA), tau=tau, tile_edge=64, config=CFG
    )
    r, net = dense_reference(x)
    sel = np.abs(r) >= tau
    ref_c = SparseAdjacency.from_coo(
        *np.nonzero(sel), r[sel], n, symmetrize=True
    )
    np.testing.assert_allclose(
        build.correlation.to_dense(), ref_c.to_dense(), atol=1e-6
    )
    assert build.adjacency.nnz == build.correlation.nnz


def test_selection_mode_validation(atlas_data):
    tn = TiledNetwork.from_data(atlas_data, BETA)
    with pytest.raises(ValueError, match="exactly one"):
        build_sparse_network(tn, config=CFG)
    with pytest.raises(ValueError, match="exactly one"):
        build_sparse_network(tn, top_k=4, tau=0.5, config=CFG)
    with pytest.raises(ValueError, match="tau must be > 0"):
        build_sparse_network(tn, tau=0.0, config=CFG)


def test_interrupt_resume_equals_uninterrupted(atlas_data, tmp_path):
    x = atlas_data
    tn = TiledNetwork.from_data(x, BETA)
    full = build_sparse_network(tn, top_k=5, tile_edge=64, config=CFG)
    ck = str(tmp_path / "atlas.npz")

    def interrupt(done, total):
        if done == 2:
            raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        build_sparse_network(
            tn, top_k=5, tile_edge=64, config=CFG,
            checkpoint_path=ck, checkpoint_every=1, progress=interrupt,
        )
    # the failure-save landed, with the pass state in x_atlas_* extras
    with np.load(ck) as z:
        extras = [key for key in z.files if key.startswith("x_atlas_")]
        # COO so-far plus the ISSUE 11 screening/transfer tally, so a
        # resume replays exact skip counters too
        assert set(extras) == {
            "x_atlas_rows", "x_atlas_cols", "x_atlas_corr",
            "x_atlas_tiles_dispatched", "x_atlas_tiles_skipped",
            "x_atlas_bytes_full", "x_atlas_bytes_moved",
        }
        assert int(z["completed"]) == 2
    resumed = build_sparse_network(
        tn, top_k=5, tile_edge=64, config=CFG,
        checkpoint_path=ck, checkpoint_every=1,
    )
    # all extras round-trip: resumed == uninterrupted, bit for bit
    assert np.array_equal(
        resumed.adjacency.to_dense(), full.adjacency.to_dense()
    )
    assert np.array_equal(
        resumed.correlation.to_dense(), full.correlation.to_dense()
    )
    assert np.array_equal(resumed.degree, full.degree)


def test_checkpoint_refuses_different_derivation(atlas_data, tmp_path):
    x = atlas_data
    ck = str(tmp_path / "atlas.npz")

    def interrupt(done, total):
        if done == 1:
            raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        build_sparse_network(
            TiledNetwork.from_data(x, BETA), top_k=5, tile_edge=64,
            config=CFG, checkpoint_path=ck, progress=interrupt,
        )
    # a different β (or threshold rule) is a different problem
    with pytest.raises(ValueError, match="different problem"):
        build_sparse_network(
            TiledNetwork.from_data(x, 3.0), top_k=5, tile_edge=64,
            config=CFG, checkpoint_path=ck,
        )
    with pytest.raises(ValueError, match="different problem"):
        build_sparse_network(
            TiledNetwork.from_data(x, BETA), tau=0.5, tile_edge=64,
            config=CFG, checkpoint_path=ck,
        )


def test_mesh_sharded_tile_pass_bit_identical(atlas_data):
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    x = atlas_data
    tn = TiledNetwork.from_data(x, BETA)
    mesh = make_mesh(n_perm_shards=2, n_row_shards=1,
                     devices=jax.devices()[:2])
    single = build_sparse_network(tn, top_k=5, tile_edge=64, config=CFG)
    sharded = build_sparse_network(
        tn, top_k=5, tile_edge=64, config=CFG, mesh=mesh
    )
    assert np.array_equal(
        sharded.adjacency.to_dense(), single.adjacency.to_dense()
    )
    assert np.array_equal(
        sharded.correlation.to_dense(), single.correlation.to_dense()
    )
    assert np.array_equal(sharded.degree, single.degree)


def test_tile_pass_telemetry_spans(atlas_data, tmp_path):
    sink = str(tmp_path / "tiles.jsonl")
    build_sparse_network(
        TiledNetwork.from_data(atlas_data, BETA), top_k=4, tile_edge=128,
        config=CFG, telemetry=sink,
    )
    events = [json.loads(l) for l in open(sink, encoding="utf-8")]
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    assert len(by_ev["tile_pass_start"]) == 1
    assert len(by_ev["tile_pass_end"]) == 1
    start = by_ev["tile_pass_start"][0]
    tiles = by_ev["tile"]
    assert len(tiles) == start["data"]["blocks"]
    # per-block events nest under the pass span; the end event closes it
    sid = start["data"]["span"]
    assert all(t["data"]["parent"] == sid for t in tiles)
    end = by_ev["tile_pass_end"][0]["data"]
    assert end["span"] == sid and end["interrupted"] is False
    assert end["blocks_done"] == start["data"]["blocks"]


def test_tile_edge_autotune_records(atlas_data, tmp_path, monkeypatch):
    from netrep_tpu.utils import autotune

    monkeypatch.setattr(
        autotune, "default_path", lambda: str(tmp_path / "at.json")
    )
    cfg = EngineConfig(autotune=True)
    build = build_sparse_network(
        TiledNetwork.from_data(atlas_data, BETA), top_k=4, tile_edge=64,
        config=cfg,
    )
    key = autotune.make_key(
        jax.default_backend(), "atlas-tiles",
        f"n{atlas_data.shape[1]}s{atlas_data.shape[0]}", 0, "topk",
    )
    samples = autotune.AutotuneCache().throughput(key, build.tile_edge)
    assert samples and samples[0] > 0
    # the recorded edge now wins the resolution for the same problem shape
    edge, _cache = autotune.resolve_tile_edge(cfg, key)
    assert edge == build.tile_edge


# ---------------------------------------------------------------------------
# Data-only module plane (module_preservation(data_only=…))
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pair():
    mixed = make_mixed_pair(220, 4, n_samples=24, seed=7)
    (dd, _dc, dn), (td, _tc, _tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return dict(dd=dd, td=td, assign=assign, specs=specs,
                pool=mixed["pool"])


ECFG = EngineConfig(chunk_size=32, power_iters=40, autotune=False)


def test_data_only_parity_with_dense_path(pair):
    """The acceptance pin: at n ≤ 512 the data-only run reproduces the
    dense path (same derivation, materialized) — statistics within the
    backend tolerance, exceedance counts and p-values bit-identical on
    CPU."""
    res = netrep_tpu.atlas_module_preservation(
        {"d": pair["dd"], "t": pair["td"]},
        module_assignments={"d": pair["assign"]}, data_only=BETA,
        discovery="d", test="t", n_perm=192, seed=1, config=ECFG,
    )
    (rdc, rdn), (rtc, rtn) = dense_reference_stats(
        pair["dd"], pair["td"], pair["specs"], BETA
    )
    ref = netrep_tpu.module_preservation(
        network={"d": rdn, "t": rtn}, correlation={"d": rdc, "t": rtc},
        data={"d": pair["dd"], "t": pair["td"]},
        module_assignments={"d": pair["assign"]},
        discovery="d", test="t", n_perm=192, seed=1, config=ECFG,
    )
    np.testing.assert_allclose(res.observed, ref.observed, atol=1e-5)
    np.testing.assert_allclose(res.nulls, ref.nulls, atol=1e-5)
    for got, want in zip(
        pv.tail_counts(res.observed, res.nulls),
        pv.tail_counts(ref.observed, ref.nulls),
    ):
        assert np.array_equal(got, want)
    assert np.array_equal(res.p_values, ref.p_values)


def test_data_only_streaming_and_adaptive(pair):
    kw = dict(
        module_assignments={"d": pair["assign"]}, data_only=BETA,
        discovery="d", test="t", seed=1, config=ECFG,
    )
    data = {"d": pair["dd"], "t": pair["td"]}
    base = netrep_tpu.atlas_module_preservation(data, n_perm=192, **kw)
    stream = netrep_tpu.atlas_module_preservation(
        data, n_perm=192, store_nulls=False, **kw
    )
    assert stream.nulls is None
    assert np.array_equal(stream.p_values, base.p_values)
    adaptive = netrep_tpu.atlas_module_preservation(
        data, n_perm=256, adaptive=True, **kw
    )
    assert adaptive.p_type == "sequential"
    assert np.isfinite(adaptive.p_values).all()


def test_data_only_checkpoint_resume(pair, tmp_path):
    kw = dict(
        module_assignments={"d": pair["assign"]}, data_only=BETA,
        discovery="d", test="t", seed=1, config=ECFG, n_perm=96,
    )
    data = {"d": pair["dd"], "t": pair["td"]}
    base = netrep_tpu.atlas_module_preservation(data, **kw)
    ckdir = str(tmp_path / "ck")
    hit = {"n": 0}

    def interrupt(done, total):
        hit["n"] += 1
        if done >= 32:
            raise KeyboardInterrupt

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        partial = netrep_tpu.atlas_module_preservation(
            data, checkpoint_dir=ckdir, checkpoint_every=32,
            progress=interrupt, **kw,
        )
    assert partial.completed < 96
    resumed = netrep_tpu.atlas_module_preservation(
        data, checkpoint_dir=ckdir, checkpoint_every=32, **kw
    )
    assert resumed.completed == 96
    assert np.array_equal(resumed.nulls, base.nulls)
    assert np.array_equal(resumed.p_values, base.p_values)


def test_data_only_engine_guards(pair):
    dd, td = pair["dd"], pair["td"]
    with pytest.raises(ValueError, match="network_from_correlation"):
        PermutationEngine(
            None, None, dd, None, None, td, pair["specs"], pair["pool"],
            config=EngineConfig(autotune=False),
        )
    with pytest.raises(ValueError, match="nothing to test"):
        PermutationEngine(
            None, None, None, None, None, None, pair["specs"],
            pair["pool"],
            config=EngineConfig(network_from_correlation=BETA,
                                autotune=False),
        )
    with pytest.raises(ValueError, match="fused"):
        PermutationEngine(
            None, None, dd, None, None, td, pair["specs"], pair["pool"],
            config=EngineConfig(network_from_correlation=BETA,
                                gather_mode="fused", autotune=False),
        )
    with pytest.raises(ValueError, match="drop the network/correlation"):
        netrep_tpu.module_preservation(
            network={"d": np.eye(3)}, data={"d": dd},
            module_assignments={"d": pair["assign"]}, data_only=BETA,
        )


def test_data_only_rejects_degenerate_columns(pair):
    bad = pair["dd"].copy()
    bad[:, 7] = 1.25
    with pytest.raises(ValueError, match="zero-variance"):
        netrep_tpu.atlas_module_preservation(
            {"d": bad, "t": pair["td"]},
            module_assignments={"d": pair["assign"]}, data_only=BETA,
            discovery="d", test="t", n_perm=8,
        )


def test_sparse_bridge_runs_config_e_engine(atlas_data):
    """The construction pass's output drops straight onto the Config E
    sparse engine: thresholded SparseAdjacency networks + the original
    data columns — atlas inputs on the existing sparse surface."""
    x = atlas_data
    build = build_sparse_network(
        TiledNetwork.from_data(x, BETA), top_k=6, tile_edge=64, config=CFG
    )
    assign = {f"node_{i}": "0" for i in range(x.shape[1])}
    for k in range(4):
        for i in range(k * 22, (k + 1) * 22):
            assign[f"node_{i}"] = str(k + 1)
    res = netrep_tpu.sparse_module_preservation(
        build.adjacency, build.adjacency, assign,
        discovery_data=x, test_data=x,
        n_perm=64, seed=0, config=EngineConfig(chunk_size=32,
                                               autotune=False),
    )
    assert np.isfinite(res.p_values).all()
    assert res.observed.shape == (4, 7)
