"""Superchunk executor + streaming tallies (``store_nulls=False``) —
ISSUE 2 acceptance: for the same key the streaming mode reproduces the
materialized mode's exceedance counts, Phipson–Smyth p-values, and
adaptive retirement decisions EXACTLY (device f32 comparisons on the
values the host widens to f64), a mid-superchunk checkpoint resumes to
the uninterrupted result, and the default path is untouched.
"""

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.ops.sequential import StopMonitor, StopRule
from netrep_tpu.parallel.engine import (
    ModuleSpec, PermutationEngine, _trim_tail_shards,
)
from netrep_tpu.utils.config import EngineConfig
from netrep_tpu.utils.profiling import NullProfile

# superchunk=3 with chunk 64 and N_PERM=300 leaves a partial tail chunk
# AND a partial tail superchunk — the masked-validity path runs in every
# parity assertion below, not just a dedicated test
CFG = EngineConfig(chunk_size=64, summary_method="eigh", superchunk=3,
                   autotune=False)
N_PERM = 300


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(320, 6, n_samples=40, seed=7)


def _engine(mixed, config=CFG):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=config
    )


@pytest.fixture(scope="module")
def runs(mixed):
    """One materialized + one streaming fixed run, same key — shared by
    the parity assertions."""
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    nulls, done = eng.run_null(N_PERM, key=0)
    stream = eng.run_null_streaming(N_PERM, observed, key=0)
    return dict(observed=observed, nulls=np.asarray(nulls), done=done,
                stream=stream)


# ---------------------------------------------------------------------------
# counts / p-value layer units
# ---------------------------------------------------------------------------

def test_counts_pvalues_match_permutation_pvalues():
    """counts_pvalues on tail_counts of a null array == permutation_pvalues
    on the array, for every alternative, including NaN observed cells and
    NaN null entries (per-cell effective counts)."""
    rng = np.random.default_rng(3)
    obs = rng.standard_normal((4, 7))
    obs[1, 2] = np.nan
    nulls = rng.standard_normal((200, 4, 7))
    nulls[150:, 0, :] = np.nan   # early-retired module
    nulls[::7, 2, 3] = np.nan    # scattered invalid draws
    hi, lo, eff = pv.tail_counts(obs, nulls)
    for alt in ("greater", "less", "two.sided"):
        want = pv.permutation_pvalues(obs, nulls, alt, total_nperm=5000)
        got = pv.counts_pvalues(obs, hi, lo, eff, alt, total_nperm=5000)
        np.testing.assert_array_equal(want, got)
    with pytest.raises(ValueError, match="alternative"):
        pv.counts_pvalues(obs, hi, lo, eff, "sideways")


def test_update_counts_equals_update():
    """Folding device-computed counts reaches the same tallies, n_used and
    retirement decisions as folding the raw null values."""
    rng = np.random.default_rng(0)
    obs = np.zeros((3, 2))
    vals = rng.standard_normal((96, 3, 2))
    rule = StopRule(h=8, min_perms=32)
    a = StopMonitor(obs, "two.sided", rule)
    b = StopMonitor(obs, "two.sided", rule)
    for i in range(0, 96, 32):
        chunk = vals[i: i + 32]
        pos = a.active_positions()
        newly_a = a.update(chunk[:, pos], 32)
        pos_b = b.active_positions()
        assert (pos == pos_b).all()
        hi = (chunk[:, pos_b] >= obs[pos_b][None]).sum(axis=0)
        lo = (chunk[:, pos_b] <= obs[pos_b][None]).sum(axis=0)
        eff = np.full_like(hi, 32)
        newly_b = b.update_counts(hi, lo, 32, eff=eff)
        np.testing.assert_array_equal(newly_a, newly_b)
    np.testing.assert_array_equal(a.hi, b.hi)
    np.testing.assert_array_equal(a.lo, b.lo)
    np.testing.assert_array_equal(a.n_used, b.n_used)
    np.testing.assert_array_equal(a.active, b.active)
    # eff rides the monitor state (streaming checkpoints restore it)
    assert "seq_eff" in b.state_arrays() and "seq_eff" not in a.state_arrays()
    c = StopMonitor(obs, "two.sided", rule)
    c.restore_state(b.state_arrays())
    np.testing.assert_array_equal(c.eff, b.eff)
    with pytest.raises(ValueError, match="expected"):
        b.update_counts(np.zeros((9, 2)), np.zeros((9, 2)), 4)


# ---------------------------------------------------------------------------
# fixed-n streaming parity (engine level)
# ---------------------------------------------------------------------------

def test_streaming_counts_match_materialized(runs):
    sc = runs["stream"]
    assert sc.completed == runs["done"] == N_PERM
    hi, lo, eff = pv.tail_counts(runs["observed"],
                                 runs["nulls"][: runs["done"]])
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)


def test_streaming_pvalues_match_materialized(runs):
    sc = runs["stream"]
    for alt in ("greater", "less", "two.sided"):
        want = pv.permutation_pvalues(
            runs["observed"], runs["nulls"][: runs["done"]], alt
        )
        got = pv.counts_pvalues(runs["observed"], sc.hi, sc.lo, sc.eff, alt)
        np.testing.assert_array_equal(want, got)


def test_streaming_invariant_to_superchunk(mixed, runs):
    """The fused dispatch depth is a pure scheduling knob: K=1 and K=8
    reproduce the K=3 tallies bit-for-bit (same keys, same fold order per
    module cell — integer adds commute)."""
    for k in (1, 8):
        cfg = EngineConfig(chunk_size=64, summary_method="eigh",
                           superchunk=k, autotune=False)
        sc = _engine(mixed, cfg).run_null_streaming(
            N_PERM, runs["observed"], key=0
        )
        np.testing.assert_array_equal(sc.hi, runs["stream"].hi)
        np.testing.assert_array_equal(sc.lo, runs["stream"].lo)
        np.testing.assert_array_equal(sc.eff, runs["stream"].eff)


def test_streaming_dispatch_and_transfer_amortization(mixed, runs):
    """The executor's reason to exist, measured: ≥2× fewer dispatches and
    ≥10× fewer device→host bytes than the materialized loop at equal
    n_perm (the bench row pins the full-size ratios; this pins the
    mechanism in CI)."""
    prof_f, prof_s = NullProfile(), NullProfile()
    eng = _engine(mixed)
    observed = runs["observed"]
    eng.run_null(N_PERM, key=0, profile=prof_f)
    eng.run_null_streaming(N_PERM, observed, key=0, profile=prof_s)
    assert prof_f.dispatches >= 2 * prof_s.dispatches, (
        prof_f.dispatches, prof_s.dispatches
    )
    assert prof_f.host_bytes >= 10 * prof_s.host_bytes, (
        prof_f.host_bytes, prof_s.host_bytes
    )
    # per-superchunk records cover the whole run
    assert sum(r["perms"] for r in prof_s.superchunks) == N_PERM


# ---------------------------------------------------------------------------
# adaptive streaming parity
# ---------------------------------------------------------------------------

def test_adaptive_streaming_matches_materialized(mixed):
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    nulls, done, fin = eng.run_null_adaptive(1200, observed, key=0)
    sc = _engine(mixed).run_null_adaptive_streaming(1200, observed, key=0)
    assert sc.finished == fin
    nulls = np.asarray(nulls)[:done]
    # identical retirement decisions ⇒ identical per-module counts
    np.testing.assert_array_equal(sc.n_perm_used, pv.effective_nperm(nulls))
    hi, lo, eff = pv.tail_counts(observed, nulls)
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)
    p_mat, _ = pv.sequential_pvalues(observed, nulls)
    p_str = pv.counts_pvalues(observed, sc.hi, sc.lo, sc.eff)
    np.testing.assert_array_equal(p_mat, p_str)


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def _interrupt_after(n):
    seen = []

    def cb(done, total):
        seen.append(done)
        if len(seen) == n:
            raise KeyboardInterrupt

    return cb


def test_streaming_checkpoint_resume_mid_superchunk(mixed, runs, tmp_path):
    ck = str(tmp_path / "stream.npz")
    part = _engine(mixed).run_null_streaming(
        N_PERM, runs["observed"], key=0, progress=_interrupt_after(1),
        checkpoint_path=ck, checkpoint_every=64,
    )
    # interrupted after the first superchunk: resume continues mid-run
    assert 0 < part.completed < N_PERM
    fin = _engine(mixed).run_null_streaming(
        N_PERM, runs["observed"], key=0, checkpoint_path=ck,
        checkpoint_every=64,
    )
    assert fin.completed == N_PERM
    np.testing.assert_array_equal(fin.hi, runs["stream"].hi)
    np.testing.assert_array_equal(fin.lo, runs["stream"].lo)
    np.testing.assert_array_equal(fin.eff, runs["stream"].eff)


def test_adaptive_streaming_checkpoint_resume(mixed, tmp_path):
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    ref = _engine(mixed).run_null_adaptive_streaming(1200, observed, key=3)
    assert ref.finished
    ck = str(tmp_path / "astream.npz")
    part = _engine(mixed).run_null_adaptive_streaming(
        1200, observed, key=3, progress=_interrupt_after(2),
        checkpoint_path=ck, checkpoint_every=64,
    )
    assert not part.finished and 0 < part.completed < ref.completed
    fin = _engine(mixed).run_null_adaptive_streaming(
        1200, observed, key=3, checkpoint_path=ck, checkpoint_every=64,
    )
    assert fin.finished and fin.completed == ref.completed
    np.testing.assert_array_equal(fin.hi, ref.hi)
    np.testing.assert_array_equal(fin.lo, ref.lo)
    np.testing.assert_array_equal(fin.eff, ref.eff)
    np.testing.assert_array_equal(fin.n_perm_used, ref.n_perm_used)


def test_streaming_and_materialized_checkpoints_never_cross(
    mixed, runs, tmp_path
):
    ck_s = str(tmp_path / "s.npz")
    ck_m = str(tmp_path / "m.npz")
    _engine(mixed).run_null_streaming(
        128, runs["observed"], key=0, checkpoint_path=ck_s
    )
    _engine(mixed).run_null(128, key=0, checkpoint_path=ck_m)
    # a materialized resume of a streaming checkpoint would fabricate NaN
    # null rows for "completed" permutations — the namespaced fingerprint
    # refuses it (and vice versa, with a mode-specific message)
    with pytest.raises(ValueError, match="different problem"):
        _engine(mixed).run_null(N_PERM, key=0, checkpoint_path=ck_s)
    with pytest.raises(ValueError, match="no streaming tallies"):
        _engine(mixed).run_null_streaming(
            N_PERM, runs["observed"], key=0, checkpoint_path=ck_m
        )


# ---------------------------------------------------------------------------
# module_preservation API / results / combine
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def api_kwargs(toy_pair_module):
    from netrep_tpu.data import pair_frames

    d, t = pair_frames(toy_pair_module)
    return dict(
        network={"disc": d["network"], "test": t["network"]},
        data={"disc": d["data"], "test": t["data"]},
        correlation={"disc": d["correlation"], "test": t["correlation"]},
        module_assignments=dict(toy_pair_module["labels"]),
        discovery="disc", test="test", n_perm=300, seed=11,
        config=EngineConfig(chunk_size=64, superchunk=2, autotune=False),
    )


def test_module_preservation_store_nulls_false(api_kwargs, tmp_path):
    from netrep_tpu import module_preservation
    from netrep_tpu.models.results import PreservationResult

    mat = module_preservation(**api_kwargs)
    strm = module_preservation(**api_kwargs, store_nulls=False)
    assert strm.nulls is None and strm.p_type == "fixed"
    assert strm.counts_hi is not None and strm.counts_eff is not None
    np.testing.assert_array_equal(mat.p_values, strm.p_values)
    assert strm.preserved_modules() == mat.preserved_modules()
    # .npz round-trip keeps counts and the nulls-absent marker
    path = str(tmp_path / "stream_result.npz")
    strm.save(path)
    back = PreservationResult.load(path)
    assert back.nulls is None
    np.testing.assert_array_equal(back.counts_hi, strm.counts_hi)
    np.testing.assert_array_equal(back.p_values, strm.p_values)
    # materialized results still round-trip with nulls and no counts
    mat.save(path)
    back_m = PreservationResult.load(path)
    assert back_m.nulls is not None and back_m.counts_hi is None


def test_module_preservation_adaptive_streaming(api_kwargs):
    from netrep_tpu import module_preservation

    am = module_preservation(**api_kwargs, adaptive=True)
    asr = module_preservation(**api_kwargs, adaptive=True,
                              store_nulls=False)
    assert asr.p_type == "sequential" and asr.nulls is None
    np.testing.assert_array_equal(am.n_perm_used, asr.n_perm_used)
    np.testing.assert_array_equal(am.p_values, asr.p_values)
    np.testing.assert_array_equal(am.module_n_perm(), asr.module_n_perm())


def test_combine_analyses_pools_counts(api_kwargs):
    from netrep_tpu import module_preservation
    from netrep_tpu.models.results import combine_analyses

    s1 = module_preservation(**api_kwargs, store_nulls=False)
    s2 = module_preservation(**{**api_kwargs, "seed": 12},
                             store_nulls=False)
    comb = combine_analyses(s1, s2)
    assert comb.nulls is None
    np.testing.assert_array_equal(comb.counts_hi, s1.counts_hi + s2.counts_hi)
    np.testing.assert_array_equal(
        comb.counts_eff, s1.counts_eff + s2.counts_eff
    )
    assert comb.completed == s1.completed + s2.completed
    # mixed merge: the materialized input is lifted into count space, so
    # the pooled p-values equal the all-streaming merge of the same runs
    m2 = module_preservation(**{**api_kwargs, "seed": 12})
    comb_mixed = combine_analyses(s1, m2)
    np.testing.assert_array_equal(comb_mixed.p_values, comb.p_values)


def test_store_nulls_false_rejects_native_backend(api_kwargs):
    from netrep_tpu import module_preservation

    kw = {k: v for k, v in api_kwargs.items() if k != "data"}
    with pytest.raises(ValueError, match="store_nulls=False requires"):
        module_preservation(**kw, backend="native", store_nulls=False)


def test_vmap_tests_streaming_parity(toy_pair_module):
    from netrep_tpu import module_preservation
    from netrep_tpu.data import pair_frames

    d, t = pair_frames(toy_pair_module)
    kw = dict(
        network={"d": d["network"], "t1": t["network"],
                 "t2": t["network"]},
        data={"d": d["data"], "t1": t["data"], "t2": t["data"]},
        correlation={"d": d["correlation"], "t1": t["correlation"],
                     "t2": t["correlation"]},
        module_assignments=dict(toy_pair_module["labels"]),
        discovery="d", test=["t1", "t2"], n_perm=200, seed=3,
        config=EngineConfig(chunk_size=64, superchunk=2, autotune=False),
        vmap_tests=True, simplify=False,
    )
    rm = module_preservation(**kw)
    rs = module_preservation(**kw, store_nulls=False)
    for t_name in ("t1", "t2"):
        assert rs["d"][t_name].nulls is None
        np.testing.assert_array_equal(
            rm["d"][t_name].p_values, rs["d"][t_name].p_values
        )


# ---------------------------------------------------------------------------
# multi-test engine parity
# ---------------------------------------------------------------------------

def test_multitest_streaming_parity():
    from netrep_tpu.parallel.multitest import MultiTestEngine

    mixed = make_mixed_pair(200, 4, n_samples=36, seed=5)
    (dd, dc, dn) = mixed["discovery"]
    (td, tc, tn) = mixed["test"]
    (td2, tc2, tn2) = make_mixed_pair(200, 4, n_samples=36, seed=6)["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    cfg = EngineConfig(chunk_size=64, summary_method="eigh", superchunk=2,
                      autotune=False)

    def make():
        return MultiTestEngine(
            dc, dn, dd, np.stack([tc, tc2]), np.stack([tn, tn2]),
            [td, td2], specs, mixed["pool"], config=cfg,
        )

    eng = make()
    observed = np.asarray(eng.observed())   # (2, K, 7)
    nulls, done = eng.run_null(200, key=0)  # 200: partial tail superchunk
    # tail_counts wants the perm axis leading
    perm_first = np.asarray(nulls)[:, :done].transpose(1, 0, 2, 3)
    hi, lo, eff = pv.tail_counts(observed, perm_first)
    sc = make().run_null_streaming(200, observed, key=0)
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)

    nulls_a, done_a, fin = make().run_null_adaptive(600, observed, key=0)
    sca = make().run_null_adaptive_streaming(600, observed, key=0)
    assert sca.finished == fin
    pf = np.asarray(nulls_a)[:, :done_a].transpose(1, 0, 2, 3)
    hi_a, lo_a, eff_a = pv.tail_counts(observed, pf)
    np.testing.assert_array_equal(sca.hi, hi_a)
    np.testing.assert_array_equal(sca.lo, lo_a)
    np.testing.assert_array_equal(sca.eff, eff_a)
    for ti in range(2):
        p_m, _ = pv.sequential_pvalues(observed[ti],
                                       np.asarray(nulls_a)[ti, :done_a])
        p_s = pv.counts_pvalues(observed[ti], sca.hi[ti], sca.lo[ti],
                                sca.eff[ti])
        np.testing.assert_array_equal(p_m, p_s)


# ---------------------------------------------------------------------------
# mesh composition
# ---------------------------------------------------------------------------

def test_streaming_parity_on_perm_mesh(mixed):
    from netrep_tpu.parallel import mesh as meshmod

    cfg = EngineConfig(chunk_size=32, summary_method="eigh", superchunk=2,
                       autotune=False)
    mesh = meshmod.make_mesh(n_perm_shards=4)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    eng = PermutationEngine(dc, dn, dd, tc, tn, td, specs, mixed["pool"],
                            config=cfg, mesh=mesh)
    observed = np.asarray(eng.observed())
    nulls, done = eng.run_null(100, key=0)
    hi, lo, eff = pv.tail_counts(observed, np.asarray(nulls)[:done])
    sc = eng.run_null_streaming(100, observed, key=0)
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)


def test_streaming_parity_fused_shard_map(mixed):
    """gather_mode='fused' + perm-axis mesh: the streaming program runs
    under shard_map with per-shard masks and psum'd counts — the exotic
    composition most likely to drift from the chunk loop."""
    from netrep_tpu.parallel import mesh as meshmod

    cfg = EngineConfig(chunk_size=32, summary_method="eigh", superchunk=2,
                       autotune=False, gather_mode="fused")
    mesh = meshmod.make_mesh(n_perm_shards=4)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    eng = PermutationEngine(dc, dn, dd, tc, tn, td, specs, mixed["pool"],
                            config=cfg, mesh=mesh)
    observed = np.asarray(eng.observed())
    nulls, done = eng.run_null(80, key=0)  # partial tail chunk
    hi, lo, eff = pv.tail_counts(observed, np.asarray(nulls)[:done])
    sc = eng.run_null_streaming(80, observed, key=0)
    np.testing.assert_array_equal(sc.hi, hi)
    np.testing.assert_array_equal(sc.lo, lo)
    np.testing.assert_array_equal(sc.eff, eff)


# ---------------------------------------------------------------------------
# satellites: tail-shard trim + throughput recording from 2 marks
# ---------------------------------------------------------------------------

class _FakeSharding:
    def __init__(self, shard_rows):
        self._rows = shard_rows

    def shard_shape(self, shape):
        return (self._rows,) + tuple(shape[1:])


class _FakeGlobalArray:
    """Stand-in for a multi-host (non-fully-addressable) chunk output —
    CI has no second host, so the trim logic is pinned structurally."""

    is_fully_addressable = False

    def __init__(self, arr, shard_rows):
        self._arr = arr
        self.sharding = _FakeSharding(shard_rows)

    @property
    def shape(self):
        return self._arr.shape

    @property
    def ndim(self):
        return self._arr.ndim

    def __getitem__(self, sel):
        return self._arr[sel]


def test_trim_tail_shards_slices_whole_shards_only():
    base = np.arange(64 * 3 * 7, dtype=np.float64).reshape(64, 3, 7)
    # single-host arrays (fully addressable) are NEVER sliced — eager-op
    # avoidance on tunneled backends
    out = _trim_tail_shards(base, 10)
    assert out is base
    # multi-host tail: keep ceil(take/shard_rows) whole shards
    fake = _FakeGlobalArray(base, shard_rows=16)
    trimmed = _trim_tail_shards(fake, 10)
    assert trimmed.shape == (16, 3, 7)
    np.testing.assert_array_equal(trimmed, base[:16])
    trimmed = _trim_tail_shards(fake, 17)
    assert trimmed.shape == (32, 3, 7)
    # full chunk: untouched
    assert _trim_tail_shards(fake, 64) is fake
    # take aligned past the last shard boundary: untouched
    assert _trim_tail_shards(fake, 49) is fake or \
        _trim_tail_shards(fake, 49).shape == (64, 3, 7)


def test_throughput_recorded_from_two_chunks(mixed, tmp_path,
                                             monkeypatch):
    """Satellite: a 2-chunk run must feed the autotune cache (the old
    `>= 3` mark guard silently dropped it)."""
    from netrep_tpu.utils import autotune

    monkeypatch.setattr(
        autotune, "default_path",
        lambda: str(tmp_path / "autotune.json"),
    )
    cfg = EngineConfig(chunk_size=64, summary_method="eigh", autotune=True)
    eng = _engine(mixed, cfg)
    eng.run_null(128, key=0)  # exactly 2 chunks
    cache = autotune.AutotuneCache()
    key, pb = eng._autotune_record[1], eng._autotune_record[2]
    assert cache.throughput(key, pb), "2-chunk run did not record"
