"""Degenerate real-world inputs the engine must handle gracefully: NaN
correlations (constant gene), modules with <2 overlapping nodes (dropped
with a warning, like the reference), nothing-to-test, and a constant
data column behind a sanitized correlation (zero-variance guard in the
standardization). None of these paths had a test naming them — and a NaN
slipping into a null on-chip would trip the watcher's selftest halt."""

import logging
import warnings

import numpy as np
import pytest

import netrep_tpu


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(3)
    n, s = 40, 20
    x = rng.standard_normal((s, n)).astype(np.float32)
    for k in range(2):
        x[:, k * 10:(k + 1) * 10] += 0.9 * rng.standard_normal(s)[:, None]
    y = rng.standard_normal((s, n)).astype(np.float32)
    cy = np.corrcoef(y, rowvar=False).astype(np.float32)
    np.fill_diagonal(cy, 1.0)
    labels = np.array(["1"] * 10 + ["2"] * 10 + ["0"] * 20)
    return x, y, cy, np.abs(cy) ** 2, labels


def _run(x, y, c, cy, nety, labels, net_d=None, **kw):
    return netrep_tpu.module_preservation(
        network={"d": np.abs(c) ** 2 if net_d is None else net_d, "t": nety},
        data={"d": x, "t": y},
        correlation={"d": c, "t": cy},
        module_assignments={"d": labels},
        discovery="d", test="t", verbose=False, **kw,
    )


def test_nan_correlation_rejected_with_informative_error(problem):
    x, y, cy, nety, labels = problem
    x = x.copy()
    x[:, 5] = 2.5  # constant gene -> NaN correlation row
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
    assert np.isnan(c).any()
    # sanitized network, NaN correlation: the CORRELATION finiteness check
    # itself must fire (an unsanitized network would mask it — review r5)
    with pytest.raises(ValueError, match="correlation .* non-finite"):
        _run(x, y, c, cy, nety, labels, n_perm=8,
             net_d=np.nan_to_num(np.abs(c) ** 2))


def test_constant_data_column_stays_finite(problem):
    # user sanitized the correlation but the raw data still carries the
    # constant column: the standardization's zero-variance guard must keep
    # every statistic and p-value finite
    x, y, cy, nety, labels = problem
    x = x.copy()
    x[:, 5] = 2.5
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
    c = np.nan_to_num(c)
    np.fill_diagonal(c, 1.0)
    res = _run(x, y, c, cy, nety, labels, n_perm=16)
    assert np.isfinite(res.observed).all()
    assert np.isfinite(res.nulls).all()
    assert np.isfinite(res.p_values).all()


def test_small_modules_dropped_with_warning(problem, caplog):
    x, y, cy, nety, labels = problem
    labels = labels.astype(object).copy()
    labels[0] = "solo"  # module with a single node
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
    np.fill_diagonal(c, 1.0)
    with caplog.at_level(logging.WARNING, logger="netrep_tpu"):
        res = _run(x, y, c, cy, nety, labels, n_perm=8)
    assert any("dropping module" in r.getMessage() for r in caplog.records)
    assert "solo" not in res.module_labels
    assert set(res.module_labels) == {"1", "2"}


def test_atlas_tile_nan_propagation_matches_corrcoef(problem):
    """ISSUE 9 satellite: the atlas plane's streaming standardization must
    reproduce dense ``np.corrcoef`` degenerate-input behavior — a
    zero-variance column makes every correlation touching it NaN, at
    EXACTLY the positions corrcoef puts them (NaN mask pinned bit-for-bit
    across a ragged tile grid; finite values agree to float64 rounding,
    since corrcoef's full-matrix GEMM and a tile GEMM legitimately differ
    in sub-block accumulation on tail tiles)."""
    from netrep_tpu.atlas import TiledNetwork

    x, *_ = problem
    x = np.asarray(x, dtype=np.float64).copy()
    x[:, 5] = 2.5   # constant gene
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        ref = np.corrcoef(x, rowvar=False)
    tn = TiledNetwork.from_data(x, 2.0, allow_degenerate=True)
    n, edge = x.shape[1], 16   # 40 columns → ragged 8-wide tail tile
    got = np.empty((n, n))
    for i0 in range(0, n, edge):
        I = np.arange(i0, min(i0 + edge, n))
        for j0 in range(0, n, edge):
            J = np.arange(j0, min(j0 + edge, n))
            got[np.ix_(I, J)] = tn.corr_tile(I, J)
    # NaN propagation bit-for-bit: same mask, whole row+column of gene 5
    assert np.array_equal(np.isnan(got), np.isnan(ref))
    assert np.isnan(got[5, :]).all() and np.isnan(got[:, 5]).all()
    finite = ~np.isnan(ref)
    np.testing.assert_allclose(got[finite], ref[finite], rtol=0, atol=1e-14)


def test_atlas_spec_rejects_zero_variance_like_the_dense_path(problem):
    """The validated spec mirrors the dense surface's rejection posture:
    where build_datasets refuses the NaN-carrying materialized
    correlation, TiledNetwork.from_data refuses the column that would
    derive it — same failure, caught at the representation that exists."""
    from netrep_tpu.atlas import TiledNetwork

    x, *_ = problem
    x = np.asarray(x, dtype=np.float64).copy()
    x[:, 5] = 2.5
    with pytest.raises(ValueError, match="zero-variance"):
        TiledNetwork.from_data(x, 2.0)


def test_all_modules_too_small_raises(problem):
    x, y, cy, nety, labels = problem
    labels = np.array(["0"] * 40, dtype=object)
    labels[0] = "solo"
    c = np.corrcoef(x, rowvar=False).astype(np.float32)
    np.fill_diagonal(c, 1.0)
    with pytest.raises(ValueError, match="nothing to test"):
        _run(x, y, c, cy, nety, labels, n_perm=8)
