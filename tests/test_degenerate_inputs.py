"""Degenerate real-world inputs the engine must handle gracefully: NaN
correlations (constant gene), modules with <2 overlapping nodes (dropped
with a warning, like the reference), nothing-to-test, and a constant
data column behind a sanitized correlation (zero-variance guard in the
standardization). None of these paths had a test naming them — and a NaN
slipping into a null on-chip would trip the watcher's selftest halt."""

import logging
import warnings

import numpy as np
import pytest

import netrep_tpu


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(3)
    n, s = 40, 20
    x = rng.standard_normal((s, n)).astype(np.float32)
    for k in range(2):
        x[:, k * 10:(k + 1) * 10] += 0.9 * rng.standard_normal(s)[:, None]
    y = rng.standard_normal((s, n)).astype(np.float32)
    cy = np.corrcoef(y, rowvar=False).astype(np.float32)
    np.fill_diagonal(cy, 1.0)
    labels = np.array(["1"] * 10 + ["2"] * 10 + ["0"] * 20)
    return x, y, cy, np.abs(cy) ** 2, labels


def _run(x, y, c, cy, nety, labels, net_d=None, **kw):
    return netrep_tpu.module_preservation(
        network={"d": np.abs(c) ** 2 if net_d is None else net_d, "t": nety},
        data={"d": x, "t": y},
        correlation={"d": c, "t": cy},
        module_assignments={"d": labels},
        discovery="d", test="t", verbose=False, **kw,
    )


def test_nan_correlation_rejected_with_informative_error(problem):
    x, y, cy, nety, labels = problem
    x = x.copy()
    x[:, 5] = 2.5  # constant gene -> NaN correlation row
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
    assert np.isnan(c).any()
    # sanitized network, NaN correlation: the CORRELATION finiteness check
    # itself must fire (an unsanitized network would mask it — review r5)
    with pytest.raises(ValueError, match="correlation .* non-finite"):
        _run(x, y, c, cy, nety, labels, n_perm=8,
             net_d=np.nan_to_num(np.abs(c) ** 2))


def test_constant_data_column_stays_finite(problem):
    # user sanitized the correlation but the raw data still carries the
    # constant column: the standardization's zero-variance guard must keep
    # every statistic and p-value finite
    x, y, cy, nety, labels = problem
    x = x.copy()
    x[:, 5] = 2.5
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
    c = np.nan_to_num(c)
    np.fill_diagonal(c, 1.0)
    res = _run(x, y, c, cy, nety, labels, n_perm=16)
    assert np.isfinite(res.observed).all()
    assert np.isfinite(res.nulls).all()
    assert np.isfinite(res.p_values).all()


def test_small_modules_dropped_with_warning(problem, caplog):
    x, y, cy, nety, labels = problem
    labels = labels.astype(object).copy()
    labels[0] = "solo"  # module with a single node
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        c = np.corrcoef(x, rowvar=False).astype(np.float32)
    np.fill_diagonal(c, 1.0)
    with caplog.at_level(logging.WARNING, logger="netrep_tpu"):
        res = _run(x, y, c, cy, nety, labels, n_perm=8)
    assert any("dropping module" in r.getMessage() for r in caplog.records)
    assert "solo" not in res.module_labels
    assert set(res.module_labels) == {"1", "2"}


def test_all_modules_too_small_raises(problem):
    x, y, cy, nety, labels = problem
    labels = np.array(["0"] * 40, dtype=object)
    labels[0] = "solo"
    c = np.corrcoef(x, rowvar=False).astype(np.float32)
    np.fill_diagonal(c, 1.0)
    with pytest.raises(ValueError, match="nothing to test"):
        _run(x, y, c, cy, nety, labels, n_perm=8)
