"""Permutation-engine tests: oracle parity of the observed pass and the null
distribution, chunking invariance, reproducibility, interrupt semantics
(SURVEY.md §4 test strategy; §7 step 3)."""

import numpy as np
import pytest

import jax

from netrep_tpu.ops import oracle
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig


def _make_setup(toy_pair):
    d = toy_pair["discovery"]
    t = toy_pair["test"]
    labels = toy_pair["labels"]
    tpos = {nm: i for i, nm in enumerate(t["names"])}

    modules = []
    for lab in sorted({v for v in labels.values() if v != "0"}):
        disc_idx, test_idx = [], []
        for i, nm in enumerate(d["names"]):
            if labels[nm] == lab and nm in tpos:
                disc_idx.append(i)
                test_idx.append(tpos[nm])
        modules.append(ModuleSpec(lab, np.array(disc_idx), np.array(test_idx)))

    overlap_pool = np.array([tpos[nm] for nm in d["names"] if nm in tpos], dtype=np.int32)
    return d, t, modules, overlap_pool


@pytest.fixture
def setup(toy_pair):
    return _make_setup(toy_pair)


def _engine(setup, **kw):
    d, t, modules, pool = setup
    cfg = kw.pop("config", EngineConfig(chunk_size=16, summary_method="eigh"))
    return PermutationEngine(
        d["correlation"], d["network"], d["data"],
        t["correlation"], t["network"], t["data"],
        modules, pool, config=cfg, **kw,
    )


def test_observed_matches_oracle(setup):
    d, t, modules, pool = setup
    eng = _engine(setup)
    obs = eng.observed()
    assert obs.shape == (len(modules), 7)

    for k, mod in enumerate(modules):
        disc = oracle.DiscoveryProps(
            d["correlation"][np.ix_(mod.disc_idx, mod.disc_idx)],
            d["network"][np.ix_(mod.disc_idx, mod.disc_idx)],
            d["data"][:, mod.disc_idx],
        )
        sub = np.ix_(mod.test_idx, mod.test_idx)
        expected = oracle.module_stats(
            disc, t["correlation"][sub], t["network"][sub], t["data"][:, mod.test_idx]
        )
        np.testing.assert_allclose(obs[k], expected, atol=2e-4)


def test_null_reproducible_and_chunk_invariant(setup):
    eng = _engine(setup)
    n1, c1 = eng.run_null(20, key=7)
    assert c1 == 20 and n1.shape == (20, 4, 7)
    assert np.isfinite(n1).all()

    eng2 = _engine(setup, config=EngineConfig(chunk_size=7, summary_method="eigh"))
    n2, _ = eng2.run_null(20, key=7)
    np.testing.assert_allclose(n1, n2, atol=1e-5)

    n3, _ = eng.run_null(20, key=8)
    assert np.abs(n1 - n3).max() > 1e-3  # different key → different null


def _synthetic_problem(seed, sizes, n_disc, n_test, n_samples):
    """Random pair + contiguous aligned ModuleSpecs (shared by the
    reconstruction and granularity tests)."""
    r = np.random.default_rng(seed)

    def build(n):
        x = r.standard_normal((n_samples, n))
        c = np.corrcoef(x, rowvar=False)
        return x, c, np.abs(c) ** 2

    d, t = build(n_disc), build(n_test)
    specs, pos = [], 0
    for k, sz in enumerate(sizes):
        idx = np.arange(pos, pos + sz, dtype=np.int32)
        specs.append(ModuleSpec(str(k + 1), idx, idx))
        pos += sz
    return d, t, specs, np.arange(n_test, dtype=np.int32)


def test_null_chunk_matches_oracle_reconstruction():
    # strongest end-to-end net: reconstruct the engine's EXACT permutations
    # on the host from the documented seeding contract (fold_in(key, i) →
    # jax.random.permutation over the pool) and recompute each null entry
    # with the NumPy oracle — validates draw → slice → gather → statistics
    # as one path, not just the kernels. Sizes cross a bucket boundary so
    # both bucket programs are covered.
    import jax.numpy as jnp

    (d_data, d_corr, d_net), (t_data, t_corr, t_net), specs, pool = \
        _synthetic_problem(31, (34, 8, 5), n_disc=70, n_test=64, n_samples=14)
    eng = PermutationEngine(
        d_corr, d_net, d_data, t_corr, t_net, t_data, specs, pool,
        config=EngineConfig(chunk_size=4, summary_method="eigh"),
    )
    n_perm = 6
    nulls, done = eng.run_null(n_perm, key=5)
    assert done == n_perm

    keys = eng.perm_keys(jax.random.key(5), 0, n_perm)
    pool_dev = jnp.asarray(pool)
    for p in range(n_perm):
        perm = np.asarray(jax.random.permutation(keys[p], pool_dev))
        off, idxs = 0, []
        for spec in specs:
            sz = len(spec.disc_idx)
            idxs.append(perm[off: off + sz])
            off += sz
        want = oracle.module_stats_for_indices(
            d_corr, d_net, d_data, t_corr, t_net, t_data,
            [spec.disc_idx for spec in specs], idxs,
        )
        np.testing.assert_allclose(
            nulls[p], want, atol=2e-4, err_msg=f"perm {p}",
        )


def test_rounded_cap_granularity():
    # default: powers of two to 32, then multiples of 32; granularity 8
    # keeps the small-module ramp but trims padding above 32 — the row
    # traffic knob for the bandwidth-bound hot loop
    c32, c8 = EngineConfig(), EngineConfig(cap_granularity=8)
    assert [c32.rounded_cap(s) for s in (3, 8, 20, 30, 33, 90, 200)] == \
           [8, 8, 32, 32, 64, 96, 224]
    assert [c8.rounded_cap(s) for s in (3, 8, 20, 30, 33, 90, 200)] == \
           [8, 8, 32, 32, 40, 96, 200]
    assert EngineConfig(cap_granularity=64).rounded_cap(90) == 128
    for bad in (4, 12, 0):
        with pytest.raises(ValueError):
            EngineConfig(cap_granularity=bad)


def test_null_invariant_under_cap_granularity():
    # masked nodes must be provably inert: the same seed's null may not
    # move when bucket padding changes. Needs a module > 32 nodes — below
    # that the power-of-two ramp gives both granularities identical caps
    # and the test is vacuous (the toy fixture's modules are all <= 15).
    d, t, specs, pool = _synthetic_problem(
        7, (38, 9), n_disc=90, n_test=80, n_samples=12
    )

    def run(g):
        eng = PermutationEngine(
            d[1], d[2], d[0], t[1], t[2], t[0], specs, pool,
            config=EngineConfig(chunk_size=16, summary_method="eigh",
                                cap_granularity=g),
        )
        return eng, eng.run_null(16, key=5)[0]

    e32, n32 = run(32)
    e8, n8 = run(8)
    # guard against vacuity: the two engines must actually pad differently
    assert {b.cap for b in e32.buckets} != {b.cap for b in e8.buckets}
    np.testing.assert_allclose(n32, n8, rtol=1e-5, atol=1e-6)


def test_null_statistics_are_calibrated(setup):
    """Null values computed by the engine match the oracle's permutation
    procedure *distributionally* (SURVEY.md §7 'RNG semantics': statistical
    equivalence, not bit parity with R)."""
    d, t, modules, pool = setup
    eng = _engine(setup)
    nulls, _ = eng.run_null(200, key=3)

    rng = np.random.default_rng(3)
    disc_props = [
        oracle.DiscoveryProps(
            d["correlation"][np.ix_(m.disc_idx, m.disc_idx)],
            d["network"][np.ix_(m.disc_idx, m.disc_idx)],
            d["data"][:, m.disc_idx],
        )
        for m in modules
    ]
    onulls = oracle.permutation_null(
        disc_props, [m.size for m in modules],
        t["correlation"], t["network"], t["data"],
        pool, 200, rng,
    )
    # Compare null means / sds per module×stat within Monte-Carlo tolerance.
    for k in range(len(modules)):
        for s in range(7):
            a, b = nulls[:, k, s], onulls[:, k, s]
            se = np.sqrt(a.var() / len(a) + b.var() / len(b)) + 1e-6
            assert abs(a.mean() - b.mean()) < 5 * se, (k, s, a.mean(), b.mean())


def test_resume(setup):
    eng = _engine(setup)
    full, _ = eng.run_null(30, key=11)
    part, done = eng.run_null(12, key=11)
    resumed = np.full((30, 4, 7), np.nan)
    resumed[:12] = part[:12]
    resumed, done2 = eng.run_null(30, key=11, nulls_init=resumed, start_perm=12)
    assert done2 == 30
    np.testing.assert_allclose(resumed, full, atol=1e-6)


def test_pool_too_small_raises(setup):
    d, t, modules, pool = setup
    with pytest.raises(ValueError, match="exceed the null candidate pool"):
        PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"],
            modules, pool[:10],
        )


def test_tiny_module_raises(setup):
    d, t, modules, pool = setup
    bad = modules + [ModuleSpec("9", np.array([0]), np.array([0]))]
    with pytest.raises(ValueError, match="fewer than 2 nodes"):
        PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"],
            bad, pool,
        )


def test_dataless_engine(setup):
    d, t, modules, pool = setup
    eng = PermutationEngine(
        d["correlation"], d["network"], None,
        t["correlation"], t["network"], None,
        modules, pool, config=EngineConfig(chunk_size=8),
    )
    obs = eng.observed()
    finite_cols = [oracle.STAT_NAMES.index(s) for s in oracle.TOPOLOGY_STATS]
    assert np.isfinite(obs[:, finite_cols]).all()
    nan_cols = [i for i in range(7) if i not in finite_cols]
    assert np.isnan(obs[:, nan_cols]).all()
    nulls, _ = eng.run_null(5, key=0)
    assert np.isfinite(nulls[:, :, finite_cols]).all()


def test_mesh_sharded_null_matches(setup):
    """Sharding the permutation chunk across an 8-device CPU mesh gives the
    same null as the single-device path (SURVEY.md §4 'multi-node without a
    real cluster')."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    mesh = Mesh(devs, ("perm",))
    eng = _engine(setup)
    ref, _ = eng.run_null(16, key=5)
    eng_sh = _engine(setup, mesh=mesh)
    got, _ = eng_sh.run_null(16, key=5)
    np.testing.assert_allclose(ref, got, atol=1e-5)


@pytest.mark.parametrize("with_data", [True, False])
def test_mxu_gather_mode_matches_direct(setup, with_data):
    """The sorted-rows+MXU gather path (gather_mode='mxu',
    ops.stats.gather_and_stats_mxu) must produce identical statistics to
    the direct 2D gather — the one-hot/permutation matmuls are exact
    selections in float32."""
    d, t, modules, pool = setup

    def run(mode):
        eng = PermutationEngine(
            d["correlation"], d["network"], d["data"] if with_data else None,
            t["correlation"], t["network"], t["data"] if with_data else None,
            modules, pool,
            config=EngineConfig(chunk_size=16, gather_mode=mode, perm_batch=4),
        )
        return eng.observed(), eng.run_null(32, key=7)[0]

    obs_d, null_d = run("direct")
    obs_m, null_m = run("mxu")
    np.testing.assert_allclose(obs_m, obs_d, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(null_m, null_d, rtol=1e-4, atol=1e-5)


def test_derived_network_matches_explicit(setup):
    """EngineConfig.network_from_correlation: deriving network submatrices
    from the gathered correlation (|corr|**beta on device, network never
    transferred) equals the explicit-network run — elementwise functions
    commute with gathers. The toy fixture's network IS |corr|**2."""
    d, t, modules, pool = setup
    for mode in ("direct", "mxu"):
        ref = PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"], modules, pool,
            config=EngineConfig(chunk_size=8, summary_method="eigh",
                                gather_mode=mode),
        )
        der = PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"], modules, pool,
            config=EngineConfig(chunk_size=8, summary_method="eigh",
                                gather_mode=mode,
                                network_from_correlation=2.0),
        )
        assert der._test_net is None  # the n x n network never hit the device
        np.testing.assert_allclose(der.observed(), ref.observed(),
                                   rtol=2e-5, atol=2e-5)
        dn, done = der.run_null(16, key=4)
        rn, _ = ref.run_null(16, key=4)
        assert done == 16
        np.testing.assert_allclose(dn, rn, rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # heaviest cross-validation in this file (VERDICT r5
# weak #3: suite wall-clock); faster siblings keep tier-1 coverage
def test_derived_network_signed_kinds_match_explicit(setup):
    """network_from_correlation=(β, kind): the signed and signed-hybrid
    WGCNA adjacency constructions derive on device exactly like unsigned —
    elementwise functions commute with gathers — so each must equal the
    run with its explicitly-stored network."""
    d, t, modules, pool = setup

    def mk(ds, kind):
        c = np.asarray(ds["correlation"])
        net = (((1.0 + c) / 2.0) ** 2 if kind == "signed"
               else np.clip(c, 0.0, None) ** 2)
        return net.astype(np.float32)

    for kind in ("signed", "signed-hybrid"):
        ref = PermutationEngine(
            d["correlation"], mk(d, kind), d["data"],
            t["correlation"], mk(t, kind), t["data"], modules, pool,
            config=EngineConfig(chunk_size=8, summary_method="eigh"),
        )
        der = PermutationEngine(
            d["correlation"], mk(d, kind), d["data"],
            t["correlation"], mk(t, kind), t["data"], modules, pool,
            config=EngineConfig(chunk_size=8, summary_method="eigh",
                                network_from_correlation=(2.0, kind)),
        )
        assert der._test_net is None  # the n x n network never hit the device
        np.testing.assert_allclose(der.observed(), ref.observed(),
                                   rtol=2e-5, atol=2e-5)
        dn, done = der.run_null(12, key=4)
        rn, _ = ref.run_null(12, key=4)
        assert done == 12
        np.testing.assert_allclose(dn, rn, rtol=2e-5, atol=2e-5)

    # claiming signed-hybrid against the fixture's |corr|**2 network must
    # fail the sample check with the kind's own formula in the message
    with pytest.raises(ValueError, match=r"max\(correlation"):
        PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"], modules, pool,
            config=EngineConfig(network_from_correlation=(2.0, "signed-hybrid")),
        )
    with pytest.raises(ValueError, match="kind must be one of"):
        EngineConfig(network_from_correlation=(2.0, "nope"))
    with pytest.raises(ValueError, match="power must be > 0"):
        EngineConfig(network_from_correlation=(-1.0, "signed"))
    with pytest.raises(ValueError, match=r"\(β, kind\) pair"):
        EngineConfig(network_from_correlation=(2.0, "signed", "extra"))


def test_derived_network_mismatch_raises(setup):
    d, t, modules, pool = setup
    with pytest.raises(ValueError, match="not \\|correlation\\|"):
        PermutationEngine(
            d["correlation"], d["network"], d["data"],
            t["correlation"], t["network"], t["data"], modules, pool,
            config=EngineConfig(network_from_correlation=3.0),  # wrong beta
        )


def test_bfloat16_storage_tracks_float32(setup):
    """dtype='bfloat16' halves the HBM traffic of the bandwidth-bound gather
    (the TPU perf lever, BASELINE.md roofline/precision notes); statistics
    must track the f32 run within bf16 rounding attenuated by the per-module
    averaging (~1e-2 at toy module sizes, far below Monte-Carlo null noise)."""
    f32 = _engine(setup, config=EngineConfig(chunk_size=16, summary_method="eigh",
                                             dtype="float32"))
    bf16 = _engine(setup, config=EngineConfig(chunk_size=16, summary_method="eigh",
                                              dtype="bfloat16"))
    np.testing.assert_allclose(bf16.observed(), f32.observed(), atol=2e-2)
    nf, cf = f32.run_null(12, key=3)
    nb, cb = bf16.run_null(12, key=3)
    assert cf == cb == 12
    # same permutation draws (keys are dtype-independent), bf16-rounded stats
    np.testing.assert_allclose(nb, nf, atol=5e-2)
    assert np.isfinite(nb).all()


def test_bfloat16_composes_with_derived_network(setup):
    """bf16 storage × derived network (|corr|**β): the two HBM-traffic
    levers used together — network submatrices derive from bf16-gathered
    correlations, statistics still track the f32 stored-network run."""
    d, t, modules, pool = setup
    t_net = np.abs(t["correlation"]) ** 2
    d_net = np.abs(d["correlation"]) ** 2
    kw = dict(chunk_size=16, summary_method="eigh")
    ref = PermutationEngine(
        d["correlation"], d_net, d["data"], t["correlation"], t_net, t["data"],
        modules, pool, config=EngineConfig(**kw, dtype="float32"),
    )
    combo = PermutationEngine(
        d["correlation"], d_net, d["data"], t["correlation"], t_net, t["data"],
        modules, pool,
        config=EngineConfig(**kw, dtype="bfloat16",
                            network_from_correlation=2.0),
    )
    nf, _ = ref.run_null(10, key=1)
    nc, _ = combo.run_null(10, key=1)
    np.testing.assert_allclose(nc, nf, atol=5e-2)
    assert np.isfinite(nc).all()
