"""Execute docs/vignette.md (VERDICT r1 item 5): every ```python block runs
verbatim, in order, in one shared namespace — the reference's vignette is
its de-facto integration test (SURVEY.md §2.1), and this keeps ours honest
the same way. A drifting document fails the suite."""

import os
import re

import pytest

VIGNETTE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "vignette.md",
)


def _blocks():
    text = open(VIGNETTE).read()
    return re.findall(r"```python\n(.*?)```", text, flags=re.S)


def test_vignette_exists_and_has_blocks():
    assert os.path.exists(VIGNETTE)
    blocks = _blocks()
    assert len(blocks) >= 8, "vignette lost its executable walkthrough"


def test_vignette_blocks_execute(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # artifacts (png, checkpoints) land in tmp
    import matplotlib

    matplotlib.use("Agg")
    ns: dict = {}
    for i, block in enumerate(_blocks()):
        try:
            exec(compile(block, f"vignette block {i + 1}", "exec"), ns)
        except Exception as e:  # pragma: no cover - failure reporting
            pytest.fail(
                f"vignette block {i + 1} failed ({type(e).__name__}: {e}):\n"
                f"{block}"
            )
    # the walkthrough's own artifacts exist
    assert (tmp_path / "module_preservation.png").exists()
    assert ns["result"].completed == 250
    assert ns["r2"].completed == 256


def test_data_docstring_points_at_real_file():
    """The round-1 verdict flagged a dangling docs/vignette.md reference in
    the public API docs; the file now exists — keep it that way."""
    import netrep_tpu.data as data_mod

    assert "docs/vignette.md" in (data_mod.load_example.__doc__ or "")
    assert os.path.exists(VIGNETTE)
