"""Exact tile screening (ISSUE 11): bound correctness as a property test
(every skipped tile's true max |r| is strictly below the active
threshold/floor at the moment it was skipped), screened top-k/τ output
bit-identical to the PR 9 unscreened path (dense-reference-checked),
including mesh-sharded and interrupt→resume compositions, the deliberate
fingerprint-sharing contract across the screening toggle (τ/top_k/degree
changes still refuse), device-side τ selection byte accounting, the
``tile_screen`` telemetry events, and the super-tile autotune entry."""

import json
import warnings

import numpy as np
import pytest

import jax

from netrep_tpu.atlas import TiledNetwork, build_sparse_network
from netrep_tpu.atlas.builder import _bound_margin
from netrep_tpu.parallel.mesh import make_mesh
from netrep_tpu.utils.config import EngineConfig

CFG = EngineConfig(autotune=False)
BETA = 2.0


def grouped_support(genes, samples, groups, seed=0):
    """Cell-type-block data: each gene expressed in one sample block
    (genes sorted by block) over a small everywhere-noise floor — the
    sparse, modular structure whose segment-norm bounds screening is
    built for."""
    rng = np.random.default_rng(seed)
    x = 0.01 * rng.standard_normal((samples, genes))
    gsz, ssz = genes // groups, samples // groups
    for g in range(groups):
        c0, c1 = g * gsz, (g + 1) * gsz if g < groups - 1 else genes
        r0, r1 = g * ssz, (g + 1) * ssz if g < groups - 1 else samples
        blk = rng.standard_normal((r1 - r0, c1 - c0))
        fac = rng.standard_normal(r1 - r0)
        blk += 1.5 * fac[:, None] * (rng.random(c1 - c0) < 0.5)
        # zero-mean within the expressing block: off-support values stay
        # near zero after global centering, the regime the segment-norm
        # bounds are sharp in
        x[r0:r1, c0:c1] += blk - blk.mean(axis=0)
    return x


def dense_r(x):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r = np.corrcoef(x, rowvar=False)
    np.fill_diagonal(r, 0.0)
    return r


@pytest.fixture(scope="module")
def structured():
    # 512 genes / 8 blocks = 64 genes per block — aligned with the
    # 64-gene tile edge the tests use, so tiles are support-coherent
    # (the layout screening is built for: genes sorted by cluster)
    return grouped_support(512, 40, 8, seed=11)


# ---------------------------------------------------------------------------
# bound correctness (property test)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kw", [dict(top_k=6), dict(tau=0.3)],
                         ids=["topk", "tau"])
@pytest.mark.parametrize("seed,genes,samples,groups", [
    (11, 512, 40, 8),      # structured: screening actually fires
    (3, 300, 20, 1),       # unstructured noise+modules: bounds near 1
])
def test_skipped_tiles_provably_below_threshold(kw, seed, genes, samples,
                                                groups):
    """The exactness property: at the moment a tile is skipped, its TRUE
    max |r| (dense float64 reference) is strictly below the threshold the
    skip was judged against — for both the coarse and refine levels, the
    static τ cut, and the running top-k floor."""
    if groups == 1:
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((samples, genes))
        for k in range(4):
            x[:, k * 22:(k + 1) * 22] += (
                1.2 * rng.standard_normal(samples)[:, None]
            )
    else:
        x = grouped_support(genes, samples, groups, seed=seed)
    r = np.abs(dense_r(x))
    edge = 64
    skips = []

    def observer(block, level, tiles, threshold):
        skips.append((block, level, np.asarray(tiles), float(threshold)))

    build_sparse_network(
        TiledNetwork.from_data(x, BETA), tile_edge=edge, config=CFG,
        screen=True, supertile=3, screen_segments=8,
        _screen_observer=observer, **kw,
    )
    checked = 0
    for block, level, tiles, threshold in skips:
        lo, hi = block * edge, min((block + 1) * edge, genes)
        for t in tiles:
            c0, c1 = t * edge, min((t + 1) * edge, genes)
            assert float(r[lo:hi, c0:c1].max()) < threshold, (
                f"block {block} skipped tile {t} at {level} level with "
                f"threshold {threshold} but true max |r| is "
                f"{r[lo:hi, c0:c1].max()}"
            )
            checked += 1
    if groups > 1:
        assert checked > 0  # the structured fixture must actually screen


# ---------------------------------------------------------------------------
# bit-identity vs the unscreened path (dense-reference-checked)
# ---------------------------------------------------------------------------


def test_screened_topk_bit_identical_dense_checked(structured):
    x = structured
    tn = TiledNetwork.from_data(x, BETA)
    un = build_sparse_network(tn, top_k=6, tile_edge=64, config=CFG,
                              degree=False)
    sc = build_sparse_network(tn, top_k=6, tile_edge=64, config=CFG,
                              screen=True, screen_segments=8)
    assert np.array_equal(un.adjacency.to_dense(), sc.adjacency.to_dense())
    assert np.array_equal(un.correlation.to_dense(),
                          sc.correlation.to_dense())
    assert sc.degree is None and un.degree is None
    assert sc.tiles_skipped > 0
    assert sc.tiles_dispatched + sc.tiles_skipped == sc.tiles_total
    # dense reference: the screened selection is the true per-row top-k
    from netrep_tpu.ops.sparse import SparseAdjacency

    r, n, k = dense_r(x), x.shape[1], 6
    rows, cols, vals = [], [], []
    for i in range(n):
        order = np.argsort(-np.abs(r[i]), kind="stable")[:k]
        rows += [i] * k
        cols += list(order)
        vals += list(r[i, order])
    ref = SparseAdjacency.from_coo(rows, cols, vals, n, symmetrize=True)
    got = sc.correlation.to_dense()
    assert ((got != 0) == (ref.to_dense() != 0)).all()
    np.testing.assert_allclose(got, ref.to_dense(), atol=1e-6)


def test_screened_tau_bit_identical_dense_checked(structured):
    x = structured
    tau = 0.3
    tn = TiledNetwork.from_data(x, BETA)
    un = build_sparse_network(tn, tau=tau, tile_edge=64, config=CFG,
                              degree=False)
    sc = build_sparse_network(tn, tau=tau, tile_edge=64, config=CFG,
                              screen=True, screen_segments=8)
    assert np.array_equal(un.correlation.to_dense(),
                          sc.correlation.to_dense())
    assert np.array_equal(un.adjacency.to_dense(), sc.adjacency.to_dense())
    assert sc.tiles_skipped > 0
    r = dense_r(x)
    sel = np.abs(r) >= tau
    got = sc.correlation.to_dense()
    assert ((got != 0) == (sel | sel.T)).all()
    np.testing.assert_allclose(got[sel], r[sel], atol=1e-6)


def test_screened_structured_fixture_skips_majority(structured):
    """The bench mechanism at test scale: on grouped-support data the
    screened top-k pass dispatches a small minority of tiles."""
    sc = build_sparse_network(
        TiledNetwork.from_data(structured, BETA), top_k=6, tile_edge=64,
        config=CFG, screen=True, screen_segments=8,
    )
    assert sc.tiles_skipped / sc.tiles_total >= 0.5
    # transfer accounting rides along and is self-consistent
    assert 0 < sc.strip_bytes_moved < sc.strip_bytes_full


def test_mesh_sharded_screened_bit_identical(structured):
    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    tn = TiledNetwork.from_data(structured, BETA)
    mesh = make_mesh(n_perm_shards=2, n_row_shards=1,
                     devices=jax.devices()[:2])
    for kw in (dict(top_k=5), dict(tau=0.3)):
        single = build_sparse_network(tn, tile_edge=64, config=CFG,
                                      screen=True, **kw)
        sharded = build_sparse_network(tn, tile_edge=64, config=CFG,
                                       screen=True, mesh=mesh, **kw)
        assert np.array_equal(sharded.correlation.to_dense(),
                              single.correlation.to_dense())
        assert np.array_equal(sharded.adjacency.to_dense(),
                              single.adjacency.to_dense())
        assert sharded.tiles_skipped == single.tiles_skipped


# ---------------------------------------------------------------------------
# checkpoint identity: screening toggle SHARES the fingerprint
# ---------------------------------------------------------------------------


def _interrupt_at(stop):
    def progress(done, total):
        if done == stop:
            raise KeyboardInterrupt
    return progress


@pytest.mark.parametrize("first,second", [(True, False), (False, True)],
                         ids=["screened-then-plain", "plain-then-screened"])
def test_resume_across_screening_toggle_bit_identical(structured, tmp_path,
                                                      first, second):
    """Screened and unscreened passes produce bit-identical output, so
    they deliberately share a checkpoint fingerprint: a pass interrupted
    under one toggle resumes under the other, bit for bit."""
    tn = TiledNetwork.from_data(structured, BETA)
    kw = dict(top_k=5, tile_edge=64, config=CFG, degree=False)
    full = build_sparse_network(tn, **kw)
    ck = str(tmp_path / "atlas.npz")
    with pytest.raises(KeyboardInterrupt):
        build_sparse_network(
            tn, screen=first, checkpoint_path=ck, checkpoint_every=1,
            progress=_interrupt_at(3), **kw,
        )
    resumed = build_sparse_network(
        tn, screen=second, checkpoint_path=ck, checkpoint_every=1, **kw
    )
    assert np.array_equal(resumed.adjacency.to_dense(),
                          full.adjacency.to_dense())
    assert np.array_equal(resumed.correlation.to_dense(),
                          full.correlation.to_dense())
    # the screening tally rode the checkpoint: the toggled-resume totals
    # still account for every real tile exactly once
    assert resumed.tiles_dispatched + resumed.tiles_skipped == \
        resumed.tiles_total


def test_screened_interrupt_resume_screened(structured, tmp_path):
    tn = TiledNetwork.from_data(structured, BETA)
    kw = dict(tau=0.3, tile_edge=64, config=CFG)
    full = build_sparse_network(tn, screen=True, **kw)
    ck = str(tmp_path / "atlas.npz")
    with pytest.raises(KeyboardInterrupt):
        build_sparse_network(
            tn, screen=True, checkpoint_path=ck, checkpoint_every=1,
            progress=_interrupt_at(2), **kw,
        )
    resumed = build_sparse_network(
        tn, screen=True, checkpoint_path=ck, checkpoint_every=1, **kw
    )
    assert np.array_equal(resumed.correlation.to_dense(),
                          full.correlation.to_dense())
    assert resumed.tiles_skipped == full.tiles_skipped
    assert resumed.tiles_dispatched == full.tiles_dispatched


def test_fingerprint_refuses_changed_threshold(structured, tmp_path):
    """A changed τ/top_k (or degree flag) is a different problem and
    refuses — only the screening toggle shares identity."""
    tn = TiledNetwork.from_data(structured, BETA)
    ck = str(tmp_path / "atlas.npz")
    with pytest.raises(KeyboardInterrupt):
        build_sparse_network(
            tn, top_k=5, tile_edge=64, config=CFG, degree=False,
            checkpoint_path=ck, progress=_interrupt_at(1),
        )
    for bad in (
        dict(top_k=6, degree=False),               # changed k
        dict(tau=0.4),                             # changed rule
        dict(top_k=5, degree=True),                # changed outputs
    ):
        with pytest.raises(ValueError, match="different problem"):
            build_sparse_network(tn, tile_edge=64, config=CFG,
                                 checkpoint_path=ck, **bad)


def test_screen_requires_degree_false(structured):
    tn = TiledNetwork.from_data(structured, BETA)
    with pytest.raises(ValueError, match="degree"):
        build_sparse_network(tn, top_k=4, tile_edge=64, config=CFG,
                             screen=True, degree=True)
    # degree defaults off under screening, on without it
    sc = build_sparse_network(tn, top_k=4, tile_edge=64, config=CFG,
                              screen=True)
    un = build_sparse_network(tn, top_k=4, tile_edge=64, config=CFG)
    assert sc.degree is None
    assert un.degree is not None and un.degree.shape == (tn.n,)


# ---------------------------------------------------------------------------
# device-side τ selection, telemetry, autotune
# ---------------------------------------------------------------------------


def test_tau_device_selection_cuts_strip_transfer(structured, tmp_path):
    """ISSUE 11 satellite: the τ path masks on device and transfers only
    surviving entries + indices — the byte delta lands on the tile-pass
    span."""
    sink = str(tmp_path / "tau.jsonl")
    build = build_sparse_network(
        TiledNetwork.from_data(structured, BETA), tau=0.3, tile_edge=64,
        config=CFG, degree=False, telemetry=sink,
    )
    assert 0 < build.strip_bytes_moved < build.strip_bytes_full
    end = [json.loads(l) for l in open(sink, encoding="utf-8")
           if '"tile_pass_end"' in l][0]["data"]
    assert end["strip_bytes_moved"] == build.strip_bytes_moved
    assert end["strip_bytes_full"] == build.strip_bytes_full
    assert end["tiles_skipped"] == 0   # unscreened pass, full grid


def test_tile_screen_telemetry_events(structured, tmp_path):
    sink = str(tmp_path / "screen.jsonl")
    build = build_sparse_network(
        TiledNetwork.from_data(structured, BETA), top_k=5, tile_edge=64,
        config=CFG, screen=True, telemetry=sink,
    )
    events = [json.loads(l) for l in open(sink, encoding="utf-8")]
    by_ev = {}
    for e in events:
        by_ev.setdefault(e["ev"], []).append(e)
    start = by_ev["tile_pass_start"][0]["data"]
    assert start["screen"] is True and start["supertile"] >= 1
    sid = start["span"]
    screens = by_ev["tile_screen"]
    assert len(screens) == start["blocks"]       # one per row block
    assert all(e["data"]["parent"] == sid for e in screens)
    assert sum(e["data"]["tiles_skipped"] for e in screens) == \
        build.tiles_skipped
    end = by_ev["tile_pass_end"][0]["data"]
    assert end["tiles_skipped"] == build.tiles_skipped
    assert end["skip_fraction"] == round(
        build.tiles_skipped / build.tiles_total, 6
    )
    assert end["nxn_bytes_avoided"] == build.tiles_skipped * 64 * 64 * 4


def test_supertile_autotune_records(structured, tmp_path, monkeypatch):
    from netrep_tpu.utils import autotune

    monkeypatch.setattr(
        autotune, "default_path", lambda: str(tmp_path / "at.json")
    )
    cfg = EngineConfig(autotune=True)
    build = build_sparse_network(
        TiledNetwork.from_data(structured, BETA), top_k=4, tile_edge=64,
        config=cfg, screen=True, supertile=3,
    )
    assert build.supertile == 3
    key = autotune.make_key(
        jax.default_backend(), "atlas-screen",
        f"n{structured.shape[1]}s{structured.shape[0]}", 0, "topk",
    )
    samples = autotune.AutotuneCache().throughput(key, 3)
    assert samples and samples[0] > 0
    # the recorded factor now wins the resolution for the same shape
    factor, _cache = autotune.resolve_supertile(cfg, key)
    assert factor == 3


def test_bound_margin_scales_with_samples():
    assert _bound_margin(32) < _bound_margin(1024)
    assert _bound_margin(8) > 0
