"""Roofline observability plane (ISSUE 18): the analytic FLOP/byte
model vs XLA's own ``cost_analysis()`` within a pinned tolerance per
program family, EXACT per-family reconciliation (chunk-span sums ==
NullProfile totals == ``null_run_end`` totals — the same integers, no
float re-derivation), peak-table / override semantics (unknown kinds
report utilisation as null, never a guess), the last-run note seam, the
``roofline`` CLI (headroom table render + ledger drift gate with exit 2
on a synthetic utilisation degrade), and the registry pin that keeps the
ISSUE 12 telemetry-registry lint package-clean."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils import costmodel as cm
from netrep_tpu.utils import perfledger as pl
from netrep_tpu.utils.config import EngineConfig
from netrep_tpu.utils.profiling import NullProfile
from netrep_tpu.utils.telemetry import KNOWN_EVENTS, Telemetry, read_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_PERM = 96


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(200, 4, n_samples=24, seed=7)


def _engine(mixed, **cfg_kw):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    cfg_kw.setdefault("chunk_size", 32)
    cfg_kw.setdefault("summary_method", "power")
    cfg_kw.setdefault("autotune", False)
    if cfg_kw.pop("data_only", False):
        cfg = EngineConfig(network_from_correlation=6.0, **cfg_kw)
        return PermutationEngine(None, None, dd, None, None, td, specs,
                                 mixed["pool"], config=cfg)
    cfg = EngineConfig(**cfg_kw)
    return PermutationEngine(dc, dn, dd, tc, tn, td, specs, mixed["pool"],
                             config=cfg)


# ---------------------------------------------------------------------------
# peak table / overrides: null, never a guess
# ---------------------------------------------------------------------------

def test_peak_table_known_kinds_and_unknown_null(monkeypatch):
    monkeypatch.delenv(cm.PEAK_OVERRIDES_ENV, raising=False)
    pf, pb = cm.device_peaks("TPU v4")  # normalized lowercase
    assert pf == 275e12 and pb == 1228e9
    # CPU and unknown kinds are deliberately absent: utilisation must
    # come back null, never a guessed number
    assert cm.device_peaks("cpu") is None
    assert cm.device_peaks("unknown") is None
    assert cm.utilisation(100.0, None) is None
    assert cm.sol_pps(10, 10, None) is None


def test_peak_overrides_env_wins_and_bad_json_ignored(monkeypatch):
    monkeypatch.setenv(cm.PEAK_OVERRIDES_ENV,
                       json.dumps({"cpu": [50e9, 10e9],
                                   "tpu v4": {"flops": 1e12, "bw": 1e11}}))
    assert cm.device_peaks("cpu") == (50e9, 10e9)
    assert cm.device_peaks("tpu v4") == (1e12, 1e11)  # override beats table
    monkeypatch.setenv(cm.PEAK_OVERRIDES_ENV, "{not json")
    assert cm.device_peaks("cpu") is None  # degrades to the table, warns


def test_sol_and_utilisation_roofline_math():
    # compute-bound: 1e9 flops/perm at 1e12 flops/s -> 1ms/perm
    assert cm.sol_pps(10**9, 10**3, (1e12, 1e9)) == pytest.approx(1000.0)
    # memory-bound: 1e6 bytes/perm at 1e9 B/s dominates 1e6 flops @ 1e12
    assert cm.sol_pps(10**6, 10**6, (1e12, 1e9)) == pytest.approx(1000.0)
    assert cm.utilisation(500.0, 1000.0) == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# the analytic model: families, integers, XLA cross-check
# ---------------------------------------------------------------------------

def test_resolve_engine_cost_families_and_integers(mixed):
    for kw, family in ((dict(gather_mode="direct"), "direct"),
                       (dict(gather_mode="mxu"), "mxu"),
                       (dict(data_only=True), "data-only")):
        cost = cm.resolve_engine_cost(_engine(mixed, **kw))
        assert cost is not None and cost.family == family
        assert isinstance(cost.flops_per_perm, int)
        assert isinstance(cost.bytes_per_perm, int)
        assert cost.flops_per_perm > 0 and cost.bytes_per_perm > 0
        # the scan-once XLA equivalent never exceeds the executed count
        assert 0 < cost.xla_flops_per_perm <= cost.flops_per_perm
    # an object without the bucket structure (native tier): None, never
    # a guessed cost
    assert cm.resolve_engine_cost(object()) is None


def test_analytic_model_vs_xla_cost_analysis(mixed):
    """The acceptance cross-check: per program family, the analytic
    model's scan-once flop count agrees with ``Compiled.cost_analysis()``
    within a pinned ratio tolerance on a small shape (measured 0.81-0.98
    on the installed jax; [0.6, 1.5] leaves drift margin while still
    catching an order-of-magnitude modeling error). Byte traffic is a
    deliberate LOWER bound: the model prices fundamental gather/slice
    movement, XLA's ``bytes accessed`` counts every intermediate."""
    import jax

    for kw in (dict(gather_mode="direct"), dict(gather_mode="mxu"),
               dict(data_only=True)):
        eng = _engine(mixed, chunk_size=16, **kw)
        cost = cm.resolve_engine_cost(eng)
        K = 16
        keys = eng.perm_keys(eng._example_run_key(), 0, K)
        compiled = jax.jit(eng.chunk_body()).lower(
            keys, *eng.chunk_args()
        ).compile()
        ca = cm.xla_cost_analysis(compiled)
        if ca is None or not ca.get("flops"):
            pytest.skip("installed jax exposes no cost_analysis()")
        ratio = (cost.xla_flops_per_perm * K) / ca["flops"]
        assert 0.6 < ratio < 1.5, (cost.family, ratio)
        if ca.get("bytes_accessed"):
            assert cost.bytes_per_perm * K <= ca["bytes_accessed"], \
                cost.family
        ma = cm.xla_memory_analysis(compiled)
        if ma is not None:
            assert ma["argument_size_in_bytes"] > 0


# ---------------------------------------------------------------------------
# acceptance: spans carry cost fields; sums reconcile EXACTLY
# ---------------------------------------------------------------------------

def _run_with_telemetry(eng, path, streaming=False):
    tel = Telemetry(path, run_id="roofline")
    prof = NullProfile()
    if streaming:
        observed = np.asarray(eng.observed())
        eng.run_null_streaming(N_PERM, observed, key=0, profile=prof,
                               telemetry=tel)
    else:
        eng.run_null(N_PERM, key=0, profile=prof, telemetry=tel)
    tel.close()
    return prof, list(read_events(str(path)))


@pytest.mark.parametrize("streaming", [False, True],
                         ids=["materialized", "streaming"])
def test_span_sums_reconcile_exactly_with_profile(mixed, tmp_path,
                                                  streaming):
    eng = _engine(mixed, superchunk=2)
    prof, events = _run_with_telemetry(
        eng, tmp_path / f"run{int(streaming)}.jsonl", streaming=streaming
    )
    spans = [e["data"] for e in events
             if e["ev"] in ("chunk", "superchunk")]
    assert spans, "no chunk/superchunk spans emitted"
    for d in spans:
        # every span carries the cost fields (acceptance criterion)
        assert isinstance(d["family"], str)
        assert isinstance(d["flops"], int) and d["flops"] > 0
        assert isinstance(d["bytes_hbm"], int) and d["bytes_hbm"] > 0
        assert d["achieved_pps"] is None or d["achieved_pps"] > 0
        assert "utilisation" in d  # null on CPU — present, never absent
    # EXACT reconciliation: span sums == NullProfile totals == run totals
    span_f = sum(d["flops"] for d in spans)
    span_b = sum(d["bytes_hbm"] for d in spans)
    assert span_f == prof.flops
    assert span_b == prof.cost_bytes
    fam = spans[0]["family"]
    assert prof.families[fam]["flops"] == span_f
    assert prof.families[fam]["bytes_hbm"] == span_b
    assert prof.families[fam]["perms"] == N_PERM
    ends = [e["data"] for e in events if e["ev"] == "null_run_end"]
    assert ends and ends[0]["flops"] == span_f
    assert ends[0]["bytes_hbm"] == span_b
    # the profile payload carries the rollup (additive — only when used)
    d = prof.as_dict()
    assert d["flops"] == span_f and d["families"][fam]["perms"] == N_PERM


def test_roofline_event_and_last_run_note(mixed, tmp_path):
    eng = _engine(mixed)
    cm.record_run_note({"stale": True})
    _, events = _run_with_telemetry(eng, tmp_path / "note.jsonl")
    rl = [e["data"] for e in events if e["ev"] == "roofline"]
    assert len(rl) == 1
    d = rl[0]
    for k in ("family", "flops_per_perm", "bytes_per_perm", "flops",
              "bytes_hbm", "device_kind", "peak_flops", "peak_bw",
              "sol_pps", "achieved_pps", "utilisation"):
        assert k in d
    assert d["achieved_pps"] > 0
    # CPU tier-1: no peak entry -> utilisation null, never a guess
    assert d["utilisation"] is None and d["peak_flops"] is None
    # the run replaced the stale note; bench rows CONSUME it
    note = cm.last_run_note(consume=True)
    assert note is not None and note["family"] == d["family"]
    assert cm.last_run_note() is None  # consumed — stale never leaks


def test_fold_and_render_roofline(mixed, tmp_path):
    eng = _engine(mixed)
    _, events = _run_with_telemetry(eng, tmp_path / "fold.jsonl")
    folded = cm.fold_roofline_events(events)
    fam = next(iter(folded["families"]))
    assert folded["families"][fam]["perms"] == N_PERM
    assert folded["run_totals"][fam]["flops"] == \
        folded["families"][fam]["flops"]
    assert len(folded["runs"]) == 1
    out = cm.render_roofline(folded)
    assert fam in out and "reconciled" in out
    # a tampered total renders the mismatch loudly
    bad = dict(folded, run_totals={fam: {"flops": 1, "bytes_hbm": 1}})
    assert "RECONCILIATION MISMATCH" in cm.render_roofline(bad)
    assert "no cost-carrying" in cm.render_roofline(
        {"families": {}, "run_totals": {}, "runs": []}
    )


def test_known_events_include_roofline():
    # ISSUE 12 registry lint stays package-clean: the new event name is
    # registered, so an emit() of it is never a lint finding
    assert "roofline" in KNOWN_EVENTS


def test_utilisation_gauged_under_cpu_peak_override(mixed, tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv(cm.PEAK_OVERRIDES_ENV,
                       json.dumps({"cpu": [50e9, 10e9]}))
    eng = _engine(mixed)
    _, events = _run_with_telemetry(eng, tmp_path / "util.jsonl")
    rl = [e["data"] for e in events if e["ev"] == "roofline"]
    assert rl and isinstance(rl[0]["utilisation"], float)
    assert rl[0]["utilisation"] > 0
    spans = [e["data"] for e in events if e["ev"] == "chunk"]
    assert any(isinstance(d["utilisation"], float) for d in spans)


# ---------------------------------------------------------------------------
# acceptance: the CLI — headroom table render, drift gate exit codes
# ---------------------------------------------------------------------------

def _cli(args, **env):
    return subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "roofline", *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", **env},
    )


def test_roofline_cli_acceptance(mixed, tmp_path):
    """The acceptance flow end to end: a telemetry-enabled CPU run's
    JSONL renders the headroom table; `--ledger --check` passes on the
    ingested history (baseline) but exits 2 on a synthetic utilisation
    degrade."""
    eng = _engine(mixed)
    run_path = tmp_path / "run.jsonl"
    ledger = str(tmp_path / "ledger.jsonl")
    os.environ["NETREP_PERF_LEDGER"] = ledger
    try:
        _run_with_telemetry(eng, run_path)
    finally:
        os.environ.pop("NETREP_PERF_LEDGER", None)
    # table render from the run JSONL
    r = _cli([str(run_path)])
    assert r.returncode == 0, r.stderr
    assert "roofline:" in r.stdout and "reconciled" in r.stdout
    # the engine run left a roofline-bearing ledger entry -> baseline OK
    entries = pl.read_entries(ledger)
    assert entries and entries[-1]["roofline_v"] == pl.ROOFLINE_VERSION
    r = _cli(["--ledger", ledger, "--check"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "baseline" in r.stdout
    # synthetic degrade: same fingerprint, signal 10x lower -> exit 2
    e = dict(entries[-1])
    rb = dict(e["roofline"])
    key = "utilisation" if rb.get("utilisation") else "achieved_pps"
    rb[key] = rb[key] / 10.0
    e["roofline"] = rb
    with open(ledger, "a") as f:
        f.write(json.dumps(e) + "\n")
    r = _cli(["--ledger", ledger, "--check"])
    assert r.returncode == 2, r.stdout + r.stderr
    assert "ROOFLINE REGRESSION" in r.stdout
    # no inputs at all: usage error, not a silent success
    assert _cli([]).returncode == 1


# ---------------------------------------------------------------------------
# ledger block + drift gate unit surface
# ---------------------------------------------------------------------------

def _rl_entry(util=None, pps=100.0, fp="cpu|direct|x", kind="cpu"):
    return pl.make_entry(
        fp, pps, "run", backend="cpu", mode="materialized", t=0.0,
        roofline={"family": "direct", "flops_per_perm": 10,
                  "bytes_per_perm": 4, "flops": 1000, "bytes_hbm": 400,
                  "device_kind": kind, "peak_flops": None, "peak_bw": None,
                  "sol_pps": None, "achieved_pps": pps,
                  "utilisation": util},
    )


def test_ledger_roofline_block_appends_after_pinned_keys():
    # the PR 13 cost_v pattern: base key order untouched, the roofline
    # block appended after — golden-shape consumers never see a shift
    base = pl.make_entry("fp", 1.0, "run", t=0.0)
    e = _rl_entry()
    assert list(e)[:len(list(base))] == list(base)
    assert list(e)[-2:] == ["roofline_v", "roofline"]
    assert e["roofline_v"] == pl.ROOFLINE_VERSION == 1


def test_check_roofline_gate_and_signal_kind_separation(tmp_path):
    path = str(tmp_path / "led.jsonl")
    # empty ledger: nothing to judge
    open(path, "w").close()
    ok, rep = pl.check_roofline(path)
    assert ok and "no roofline entries" in rep
    # pps-gauged history (CPU: utilisation null), steady then degraded
    for pps in (100.0, 110.0, 95.0):
        pl.append_entry(_rl_entry(pps=pps), path)
    ok, rep = pl.check_roofline(path)
    assert ok
    pl.append_entry(_rl_entry(pps=10.0), path)
    ok, rep = pl.check_roofline(path)
    assert not ok and "ROOFLINE REGRESSION" in rep
    # a utilisation-gauged entry (device now known) must NOT be judged
    # against the pps history — different signal kind, new baseline
    pl.append_entry(_rl_entry(util=0.4, kind="tpu v4"), path)
    ok, rep = pl.check_roofline(path)
    assert ok and "baseline" in rep
    pl.append_entry(_rl_entry(util=0.38, kind="tpu v4"), path)
    ok, _ = pl.check_roofline(path)
    assert ok
    pl.append_entry(_rl_entry(util=0.04, kind="tpu v4"), path)
    ok, rep = pl.check_roofline(path)
    assert not ok and "utilisation" in rep


def test_serve_replica_util_column_and_note_peek():
    """The serve plane's utilisation gauge: `top` renders a `util`
    column from replica rows (``-`` until a run lands or when the
    device kind has no peak entry), and the scheduler reads the last-run
    note with PEEK semantics — ``stats()`` is polled, so the note must
    survive repeated reads (bench rows are the consuming reader)."""
    from netrep_tpu.serve.scheduler import PreservationServer
    from netrep_tpu.serve.top import render, render_replica_table, snapshot

    snap = snapshot({
        "uptime_s": 1.0, "accepting": True, "brownout": False,
        "queue_depth": 0, "done": 0, "tenants": {},
        "replicas": {
            "r0": {"alive": True, "queue_depth": 0, "backlog_perms": 0,
                   "rate_pps": 100.0, "utilisation": 0.42, "packs": 1,
                   "done": 2},
            "r1": {"alive": True, "queue_depth": 0, "backlog_perms": 0,
                   "rate_pps": 50.0, "utilisation": None, "packs": 0,
                   "done": 0},
        },
    })
    assert [r["utilisation"] for r in snap["replicas"]] == [0.42, None]
    table = render_replica_table(snap["replicas"])
    assert "util" in table.splitlines()[0]
    r0_line, r1_line = table.splitlines()[1:3]
    assert "0.42" in r0_line
    assert " - " in r1_line  # null, never a guess
    assert "util" in render(snap)
    # the scheduler's note seam: peek leaves the note in place
    cm.record_run_note({"family": "direct", "achieved_pps": 123.0,
                        "utilisation": None})
    try:
        assert PreservationServer._roofline_note()["achieved_pps"] == 123.0
        assert PreservationServer._roofline_note() is not None  # still there
    finally:
        cm.last_run_note(consume=True)


def test_entry_from_bench_row_carries_roofline():
    row = {"metric": "north", "perms_per_sec": 50.0, "device": "TPU v4",
           "roofline": {"family": "mxu", "utilisation": 0.3}}
    e = pl.entry_from_bench_row(row)
    assert e is not None and e["roofline"]["family"] == "mxu"
    assert pl.entry_from_bench_row(
        {"metric": "north", "perms_per_sec": 50.0, "device": "TPU v4",
         "roofline": "bogus"}
    ).get("roofline") is None
