"""Hierarchical trace spans + Perfetto export (ISSUE 5): span-tree
reconstruction, golden-shape Chrome trace JSON, the compile/dispatch/
transfer/host time split, and span-tree determinism under the
fault-injection harness. Everything runs on CPU (the exporter itself is
pure-offline and touches no backend at all)."""

import json

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig, FaultPolicy
from netrep_tpu.utils.telemetry import Telemetry
from netrep_tpu.utils.trace import (
    build_span_tree, merge_events, render_perfetto, time_split,
    write_perfetto,
)


def _ev(t, ev, run="r1", **data):
    return {"v": 1, "t": t, "m": t - 100.0, "run": run, "ev": ev,
            "data": data}


#: hand-written stream covering every exporter branch: a begin/end span
#: pair (null_run), a closed child span (chunk), a timed leaf without a
#: span id (dispatch), an instant (retry_attempt), and an end-of-run
#: compile_span estimate that must render at its PARENT's start
SYNTH = [
    _ev(100.0, "null_run_start", span="s1", mode="materialized"),
    _ev(100.5, "dispatch", parent="s2", s=0.4, start=0, take=16),
    _ev(100.6, "retry_attempt", parent="s2", attempt=1),
    _ev(100.7, "chunk", span="s2", parent="s1", s=0.6, take=16),
    _ev(101.0, "compile_span", parent="s1", s=0.3, key="k1"),
    _ev(101.0, "null_run_end", span="s1", s=1.0, completed=16),
]


# ---------------------------------------------------------------------------
# span-tree reconstruction
# ---------------------------------------------------------------------------

def test_build_span_tree_pairs_and_nests():
    spans, instants = build_span_tree(SYNTH)
    # s1 closed by null_run_end, s2 by chunk, dispatch + compile_span are
    # synthetic-id leaves; retry_attempt is the lone instant
    assert set(spans) == {"s1", "s2", "e1", "e4"}
    s1, s2 = spans["s1"], spans["s2"]
    assert s1["name"] == "null_run" and s1["parent"] is None
    assert s1["t_start"] == pytest.approx(100.0) and s1["dur_s"] == 1.0
    assert s2["name"] == "chunk" and s2["parent"] == "s1"
    assert s2["t_start"] == pytest.approx(100.1)  # 100.7 - 0.6
    assert spans["e1"]["name"] == "dispatch"
    assert spans["e1"]["parent"] == "s2"
    assert s1["children"] == ["s2", "e4"] and s2["children"] == ["e1"]
    assert (s1["depth"], s2["depth"], spans["e1"]["depth"]) == (1, 2, 3)
    assert len(instants) == 1
    assert instants[0]["name"] == "retry_attempt"
    assert instants[0]["parent"] == "s2"


def test_begin_only_span_renders_zero_width():
    """A crashed run's unclosed span must still render (zero width at its
    begin time), never raise."""
    spans, _ = build_span_tree([SYNTH[0]])
    assert spans["s1"]["dur_s"] == 0.0
    assert spans["s1"]["t_start"] == spans["s1"]["t_end"] == 100.0


# ---------------------------------------------------------------------------
# Perfetto export: golden shape
# ---------------------------------------------------------------------------

def test_perfetto_golden_shape():
    """Pinned export contract (ISSUE 5 acceptance): stable per-event key
    order, µs integer ts/dur relative to the earliest event, pid = run in
    first-appearance order, tid = span depth, compile_span at parent
    start, instants on the parent's child row."""
    doc = render_perfetto(SYNTH)
    assert list(doc) == ["traceEvents", "displayTimeUnit"]
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert meta[0] == {"name": "process_name", "ph": "M", "pid": 1,
                      "args": {"name": "run r1"}}
    assert {(m["pid"], m["tid"]) for m in meta if m["name"] == "thread_name"
            } == {(1, 1), (1, 2), (1, 3)}
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"null_run", "chunk", "dispatch", "compile_span"}
    for e in xs.values():
        # pinned key order — the golden shape downstream viewers rely on
        assert list(e) == ["name", "ph", "ts", "dur", "pid", "tid", "args"]
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
    assert xs["null_run"] == {
        "name": "null_run", "ph": "X", "ts": 0, "dur": 1_000_000,
        "pid": 1, "tid": 1, "args": {"mode": "materialized",
                                     "completed": 16, "span": "s1"},
    }
    assert xs["chunk"]["ts"] == 100_000 and xs["chunk"]["dur"] == 600_000
    assert xs["chunk"]["tid"] == 2
    assert xs["dispatch"]["ts"] == 100_000  # 100.5 - 0.4s, in µs
    assert xs["dispatch"]["tid"] == 3
    # the compile estimate is emitted at run END but renders at the run
    # span's START (compile happens first)
    assert xs["compile_span"]["ts"] == 0
    assert xs["compile_span"]["dur"] == 300_000
    inst = [e for e in evs if e["ph"] == "i"]
    assert len(inst) == 1 and inst[0]["name"] == "retry_attempt"
    assert inst[0]["tid"] == 3  # parent chunk's depth + 1


def test_write_perfetto_round_trips(tmp_path):
    src = tmp_path / "run.jsonl"
    with open(src, "w") as f:
        for e in SYNTH:
            f.write(json.dumps(e) + "\n")
    out = tmp_path / "trace.json"
    n = write_perfetto(str(src), str(out))
    doc = json.load(open(out))
    assert n == len(doc["traceEvents"]) > 0


# ---------------------------------------------------------------------------
# time split
# ---------------------------------------------------------------------------

def test_trace_id_propagates_to_descendants():
    """ISSUE 13: a span carrying ``trace`` gives it to its whole subtree
    (the request subtree inherits the client-minted id); unrelated spans
    stay untraced."""
    events = [
        _ev(100.0, "serve_start", span="s1"),
        _ev(100.1, "request_received", span="s2", parent="s1",
            trace="t" * 32, tenant="a"),
        _ev(100.5, "request_done", span="s2", s=0.4, tenant="a"),
        _ev(100.6, "pack", span="s3", parent="s1", s=0.3),
    ]
    spans, _ = build_span_tree(events)
    assert spans["s2"]["args"]["trace"] == "t" * 32
    assert "trace" not in spans["s3"]["args"]
    assert "trace" not in spans["s1"]["args"]


def test_merge_events_namespaces_and_groups_by_trace(tmp_path):
    """Two files, two runs, one trace id (a client + a restarted server,
    or two server generations): merged export namespaces the per-bus span
    ids (no ``s1`` collision) and renders every traced span under ONE
    trace-named pid; untraced logs keep the per-run pids unchanged."""
    tr = "f" * 32
    gen1 = [
        _ev(100.0, "request_received", run="runA", span="s1", trace=tr),
        # crashed: begin-only
    ]
    gen2 = [
        _ev(200.0, "request_received", run="runB", span="s1", trace=tr),
        _ev(200.9, "request_done", run="runB", span="s1", s=0.8),
    ]
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    for p, evs in ((p1, gen1), (p2, gen2)):
        with open(p, "w", encoding="utf-8") as f:
            for e in evs:
                f.write(json.dumps(e) + "\n")
    merged = merge_events([p1, p2])
    sids = {e["data"]["span"] for e in merged}
    assert sids == {"runA:s1", "runB:s1"}     # no collision by design
    doc = render_perfetto(merged)
    rows = [r for r in doc["traceEvents"] if r.get("ph") == "X"]
    assert len(rows) == 2
    assert len({r["pid"] for r in rows}) == 1
    names = {m["args"]["name"] for m in doc["traceEvents"]
             if m.get("name") == "process_name"
             and m["pid"] == rows[0]["pid"]}
    assert any(n.startswith("trace ") for n in names)
    # multi-file write_perfetto drives the same merge path
    out = str(tmp_path / "merged.json")
    n = write_perfetto([p1, p2], out)
    assert n == len(doc["traceEvents"])


def test_time_split_sums_to_total():
    split = time_split(SYNTH)
    assert split["n_runs"] == 1 and split["total_s"] == 1.0
    # compile (0.3) is a carve-out of the measured dispatch time (0.4)
    assert split["compile_s"] == pytest.approx(0.3)
    assert split["dispatch_s"] == pytest.approx(0.1)
    total = (split["compile_s"] + split["dispatch_s"] + split["transfer_s"]
             + split["host_s"])
    assert total == pytest.approx(split["total_s"], rel=1e-9)


def test_time_split_none_without_runs():
    assert time_split([SYNTH[1]]) is None


# ---------------------------------------------------------------------------
# real-run round trip + determinism under the fault harness
# ---------------------------------------------------------------------------

CFG = EngineConfig(chunk_size=16, summary_method="eigh", autotune=False)
N_PERM = 64


@pytest.fixture(scope="module")
def eng():
    mixed = make_mixed_pair(120, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=CFG
    )


def _tree_shape(path):
    """Structure of a run's span tree, stripped of timing: (ev, span,
    parent) triples in emit order — the determinism invariant."""
    out = []
    for e in map(json.loads, open(path)):
        d = e["data"]
        out.append((e["ev"], d.get("span"), d.get("parent")))
    return out


def test_real_run_round_trip(eng, tmp_path):
    """Acceptance: a telemetry-enabled CPU run round-trips JSONL → span
    tree → Perfetto with every chunk/dispatch event owned by exactly one
    parent span, and the time split sums to the run total within 1%."""
    path = tmp_path / "run.jsonl"
    tel = Telemetry(path, run_id="rt")
    nulls, done = eng.run_null(N_PERM, key=0, telemetry=tel)
    tel.close()
    assert done == N_PERM
    events = [e for e in map(json.loads, open(path))]
    spans, instants = build_span_tree(events)
    roots = [s for s in spans.values() if s["parent"] not in spans]
    assert [r["name"] for r in roots] == ["null_run"]
    for e in events:
        if e["ev"] in ("chunk", "dispatch", "retry_attempt"):
            p = e["data"].get("parent")
            assert p in spans, f"{e['ev']} not owned by a known span"
    # 64 perms / 16 chunk = 4 chunks, each with its own dispatch leaf
    assert sum(1 for s in spans.values() if s["name"] == "chunk") == 4
    assert sum(1 for s in spans.values() if s["name"] == "dispatch") == 4
    assert sum(1 for s in spans.values() if s["name"] == "compile_span") == 1
    split = time_split(events)
    parts = (split["compile_s"] + split["dispatch_s"] + split["transfer_s"]
             + split["host_s"])
    assert abs(parts - split["total_s"]) <= 0.01 * split["total_s"]
    out = tmp_path / "trace.json"
    assert write_perfetto(str(path), str(out)) == len(
        json.load(open(out))["traceEvents"])


def test_span_tree_deterministic_under_faults(eng, tmp_path):
    """Two identical runs under the same injected-fault plan produce the
    SAME span tree — ids are a per-bus counter, not UUIDs — and retries
    nest under their chunk's span."""
    shapes = []
    for i in range(2):
        path = tmp_path / f"fault{i}.jsonl"
        tel = Telemetry(path, run_id="det")
        pol = FaultPolicy(plan="transient@16x2;transient@48",
                          backoff_base_s=0.0, backoff_jitter=0.0)
        nulls, done = eng.run_null(
            N_PERM, key=0, telemetry=tel, fault_policy=pol
        )
        tel.close()
        assert done == N_PERM
        shapes.append(_tree_shape(path))
    assert shapes[0] == shapes[1]
    # every retry/fault event nests under the chunk span that owned the
    # dispatch it fired in
    events = [e for e in map(json.loads, open(tmp_path / "fault0.jsonl"))]
    spans, _ = build_span_tree(events)
    chunk_span_of = {}  # dispatch start -> chunk span id
    for e in events:
        if e["ev"] == "dispatch":
            chunk_span_of[e["data"]["start"]] = e["data"]["parent"]
    n_checked = 0
    for e in events:
        if e["ev"] in ("fault_injected", "retry_attempt"):
            assert e["data"]["parent"] == chunk_span_of[e["data"]["start"]]
            n_checked += 1
    assert n_checked == 6  # 3 faults + 3 retries
