"""Black-box flight recorder, pinned anomaly detectors, and diagnostic
bundles (ISSUE 20).

Covers: ring overflow determinism (entry AND byte bounds, oldest-first
eviction, never below one entry), the pinned detector catalogue, one
readable bundle per detector, ``NETREP_FAULT_PLAN`` device-loss drills
across all four null-loop modes (ring captures the trigger plus the
preceding chunk beats WITHOUT any JSONL sink), bundle redaction (journal
tails carry digests, never raw payloads), the ``dump`` wire op and
SIGUSR2 on a live server, coordinator bundle collection on fleet kill
and eviction handoff, auto-bundle cooldown, and the pinned bit-identity
guarantee: recorder-on results equal recorder-off results.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils import bundle, detectors, flightrec
from netrep_tpu.utils import telemetry as tm
from netrep_tpu.utils.config import EngineConfig, FaultPolicy
from netrep_tpu.utils.faults import DeviceLostError
from netrep_tpu.utils.telemetry import Telemetry

CFG = EngineConfig(chunk_size=16, summary_method="eigh", superchunk=2,
                   autotune=False)
N_PERM = 64

MODES = ("fixed", "adaptive", "stream", "adaptive_stream")


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(120, 3, n_samples=16, seed=7)


@pytest.fixture(scope="module")
def eng(mixed):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=CFG
    )


@pytest.fixture(scope="module")
def observed(eng):
    return np.asarray(eng.observed())


def _run(eng, mode, observed, **kw):
    if mode == "fixed":
        nulls, done = eng.run_null(N_PERM, key=0, **kw)
        return "mat", nulls, done, done == N_PERM
    if mode == "adaptive":
        nulls, done, fin = eng.run_null_adaptive(
            N_PERM, observed, key=0, **kw
        )
        return "mat", nulls, done, fin
    if mode == "stream":
        sc = eng.run_null_streaming(N_PERM, observed, key=0, **kw)
        return "sc", sc, sc.completed, sc.completed == N_PERM
    sc = eng.run_null_adaptive_streaming(N_PERM, observed, key=0, **kw)
    return "sc", sc, sc.completed, sc.finished


@pytest.fixture(autouse=True)
def forensics():
    """Every test starts with the always-on recorder installed (package
    import did that), an empty ring, and armed detector cooldowns."""
    assert flightrec.recorder() is not None, \
        "package import must install the flight recorder"
    flightrec.recorder().clear()
    detectors.reset()
    yield
    detectors.reset()


def _record(i, payload=None):
    return {"v": 1, "t": float(i), "m": {}, "run": "r",
            "ev": f"e{i}", "data": payload or {"i": i}}


# ---------------------------------------------------------------------------
# ring bounds + determinism
# ---------------------------------------------------------------------------

def test_ring_entry_bound_evicts_oldest_first():
    rec = flightrec.FlightRecorder(max_entries=4, max_bytes=1 << 20)
    for i in range(10):
        rec.record(_record(i))
    evs = [e["ev"] for e in rec.snapshot()]
    assert evs == ["e6", "e7", "e8", "e9"]   # strictly the newest suffix
    st = rec.stats()
    assert st["entries"] == 4 and st["n_seen"] == 10
    assert st["n_evicted"] == 6


def test_ring_byte_bound_honored_never_below_one_entry():
    line_len = len(json.dumps(_record(0)).encode())
    rec = flightrec.FlightRecorder(max_entries=1 << 20,
                                   max_bytes=3 * line_len)
    for i in range(10):
        rec.record(_record(i))
    st = rec.stats()
    assert st["bytes"] <= 3 * line_len
    assert [e["ev"] for e in rec.snapshot()] == ["e7", "e8", "e9"]
    # one entry bigger than the whole budget is still retained: the
    # newest event must never be dropped by its own size
    tiny = flightrec.FlightRecorder(max_entries=8, max_bytes=4)
    tiny.record(_record(0, {"pad": "x" * 100}))
    assert tiny.stats()["entries"] == 1
    assert tiny.snapshot()[0]["ev"] == "e0"


def test_ring_eviction_is_deterministic():
    a = flightrec.FlightRecorder(max_entries=5, max_bytes=400)
    b = flightrec.FlightRecorder(max_entries=5, max_bytes=400)
    for i in range(50):
        a.record(_record(i))
        b.record(_record(i))
    assert a.lines() == b.lines()
    assert a.stats() == b.stats()


def test_ring_env_bounds(monkeypatch):
    monkeypatch.setenv(flightrec.ENV_ENTRIES, "7")
    monkeypatch.setenv(flightrec.ENV_BYTES, "12345")
    rec = flightrec.FlightRecorder()
    assert rec.max_entries == 7 and rec.max_bytes == 12345
    monkeypatch.setenv(flightrec.ENV_ENTRIES, "bogus")
    monkeypatch.setenv(flightrec.ENV_BYTES, "-1")
    rec = flightrec.FlightRecorder()
    assert rec.max_entries == flightrec.DEFAULT_ENTRIES
    assert rec.max_bytes == flightrec.DEFAULT_BYTES


def test_ring_dump_round_trips(tmp_path):
    rec = flightrec.FlightRecorder(max_entries=8, max_bytes=1 << 20)
    for i in range(5):
        rec.record(_record(i))
    out = str(tmp_path / "ring.jsonl")
    assert rec.dump_jsonl(out) == 5
    assert [json.loads(ln)["ev"] for ln in open(out)] == [
        "e0", "e1", "e2", "e3", "e4"]


# ---------------------------------------------------------------------------
# pinned detector catalogue
# ---------------------------------------------------------------------------

def test_detector_catalogue_is_pinned():
    assert detectors.DETECTORS == (
        "stall_escalation", "device_lost", "degraded_to_cpu", "slo_burn",
        "brownout", "replica_failover", "replica_evicted", "perf_drift",
        "roofline_drift", "checkpoint_refused", "aot_refused",
    )
    # every event-mapped trigger resolves to a pinned detector, off a
    # known event name
    for ev, name in detectors.EVENT_DETECTORS.items():
        assert name in detectors.DETECTORS
        assert ev in tm.KNOWN_EVENTS
    with pytest.raises(ValueError, match="unknown detector"):
        detectors.fire("made_up_detector")


def test_every_detector_produces_a_readable_bundle(tmp_path, monkeypatch):
    """The acceptance loop: each of the pinned detectors, when fired,
    yields one bundle whose ring holds the anomaly and whose rendered
    report names the detector."""
    monkeypatch.setenv(detectors.BUNDLE_DIR_ENV, str(tmp_path))
    tel = Telemetry(run_id="drill")
    for name in detectors.DETECTORS:
        path = detectors.fire(name, telemetry=tel, probe=1)
        assert path is not None and os.path.isdir(path), name
        man = json.load(open(os.path.join(path, "manifest.json")))
        assert man["reason"] == name and man["format"] == bundle.FORMAT_VERSION
        ring = [json.loads(ln)
                for ln in open(os.path.join(path, "flight_ring.jsonl"))]
        fired = [e for e in ring if e["ev"] == "anomaly_detected"
                 and e["data"].get("detector") == name]
        assert fired and fired[-1]["data"]["probe"] == 1, name
        report = bundle.render_report(path)
        assert name in report and "detector verdicts:" in report, name


def test_scan_maps_events_and_never_retriggers_on_forensics(tmp_path,
                                                            monkeypatch):
    monkeypatch.setenv(detectors.BUNDLE_DIR_ENV, str(tmp_path))
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry(path, run_id="scan")
    # an event-mapped anomaly riding a user bus: the flight observer
    # scans it and the detector answers ON THAT BUS
    tel.emit("serve_brownout_enter", backlog_s=9.0)
    tel.close()
    evs = [json.loads(ln) for ln in open(path)]
    anom = [e for e in evs if e["ev"] == "anomaly_detected"]
    assert len(anom) == 1
    assert anom[0]["data"]["detector"] == "brownout"
    assert anom[0]["data"]["backlog_s"] == 9.0
    # exactly one anomaly in the ring too: the anomaly_detected /
    # flightrec_dump / bundle_written events it caused were not
    # themselves re-scanned into more anomalies
    ring = flightrec.recorder().snapshot()
    assert len([e for e in ring if e["ev"] == "anomaly_detected"]) == 1
    assert os.path.isdir(str(tmp_path / "netrep-bundle-brownout"))


def test_auto_bundle_cooldown_limits_storms(tmp_path, monkeypatch):
    monkeypatch.setenv(detectors.BUNDLE_DIR_ENV, str(tmp_path))
    tel = Telemetry(run_id="storm")
    first = detectors.fire("device_lost", telemetry=tel, take=16)
    assert first is not None
    # a storm of the same detector inside the cooldown: no second bundle
    for _ in range(5):
        assert detectors.fire("device_lost", telemetry=tel, take=16) is None
    # a DIFFERENT detector is on its own clock
    assert detectors.fire("slo_burn", telemetry=tel) is not None
    # reset re-arms (what tests and a new incident window rely on)
    detectors.reset()
    second = detectors.fire("device_lost", telemetry=tel, take=16)
    assert second is not None and second != first


def test_checkpoint_refusal_fires_detector(tmp_path, monkeypatch):
    monkeypatch.setenv(detectors.BUNDLE_DIR_ENV, str(tmp_path))
    from netrep_tpu.utils.checkpoint import validate_identity

    ck = {"fingerprint": np.frombuffer(b"old", dtype=np.uint8),
          "key_data": np.zeros(2, np.uint32), "completed": 8}
    with pytest.raises(ValueError, match="different problem"):
        validate_identity(ck, np.zeros(2, np.uint32),
                          np.frombuffer(b"new", dtype=np.uint8), "p")
    ring = flightrec.recorder().snapshot()
    fired = [e for e in ring if e["ev"] == "anomaly_detected"]
    assert fired and fired[-1]["data"]["detector"] == "checkpoint_refused"
    assert fired[-1]["data"]["why"] == "fingerprint_mismatch"
    assert os.path.isdir(str(tmp_path / "netrep-bundle-checkpoint_refused"))


# ---------------------------------------------------------------------------
# NETREP_FAULT_PLAN drills: all four null-loop modes, no JSONL sink
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", MODES)
def test_fault_plan_drill_bundles_with_ring_context(eng, observed, mode,
                                                    tmp_path, monkeypatch):
    """The headline capability: NO telemetry sink anywhere, a device loss
    injected by the env drill switch alone — and the auto-collected
    bundle's ring still holds the chunk beats leading up to the trigger,
    the trigger itself, and the detector verdict."""
    monkeypatch.setenv("NETREP_FAULT_PLAN", "device_lost@32")
    monkeypatch.setenv(detectors.BUNDLE_DIR_ENV, str(tmp_path))
    with pytest.raises(DeviceLostError):
        _run(eng, mode, observed,
             fault_policy=FaultPolicy(backoff_base_s=0.0,
                                      backoff_jitter=0.0))
    bdir = str(tmp_path / "netrep-bundle-device_lost")
    assert os.path.isdir(bdir)
    ring = [json.loads(ln)
            for ln in open(os.path.join(bdir, "flight_ring.jsonl"))]
    evs = [e["ev"] for e in ring]
    assert "device_lost" in evs, mode
    trigger = evs.index("device_lost")
    # permutations [0, 32) completed before the injected loss: the ring
    # shows the run's heartbeat (dispatch beats plus the committed
    # chunk/superchunk) leading INTO the incident
    beats = [ev for ev in evs[:trigger]
             if ev in ("dispatch", "chunk", "superchunk")]
    assert len(beats) >= 2, (mode, evs[:trigger])
    assert any(ev in ("chunk", "superchunk") for ev in beats), \
        (mode, evs[:trigger])
    verdicts = [e for e in ring if e["ev"] == "anomaly_detected"]
    assert verdicts and verdicts[-1]["data"]["detector"] == "device_lost"
    report = bundle.render_report(bdir)
    assert "device_lost" in report and "timeline" in report


# ---------------------------------------------------------------------------
# bit-identity: recorder on == recorder off, telemetry off throughout
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ("fixed", "adaptive_stream"))
def test_recorder_on_bit_identical_to_recorder_off(eng, observed, mode):
    """The pinned guarantee that lets the recorder stay always-on: a
    telemetry-off run with the flight recorder installed produces
    results bit-identical to one with it fully uninstalled."""
    kind_on, on, done_on, fin_on = _run(eng, mode, observed)
    assert flightrec.recorder().stats()["n_seen"] > 0  # it DID observe
    flightrec.uninstall()
    try:
        assert tm.current() is None   # ambient stack truly empty again
        kind_off, off, done_off, fin_off = _run(eng, mode, observed)
    finally:
        flightrec.install()
    assert (done_on, fin_on) == (done_off, fin_off)
    if kind_on == "mat":
        np.testing.assert_array_equal(np.asarray(on), np.asarray(off))
    else:
        assert (on.hi == off.hi).all() and (on.lo == off.lo).all()
        assert (on.eff == off.eff).all()
        if on.n_perm_used is not None:
            np.testing.assert_array_equal(on.n_perm_used, off.n_perm_used)


def test_flightrec_env_opt_out(monkeypatch):
    flightrec.uninstall()
    try:
        monkeypatch.setenv(flightrec.ENV_TOGGLE, "0")
        assert flightrec.install() is None
        assert flightrec.recorder() is None and flightrec.bus() is None
        monkeypatch.delenv(flightrec.ENV_TOGGLE)
    finally:
        flightrec.install()
    assert flightrec.recorder() is not None


# ---------------------------------------------------------------------------
# bundle redaction: digests only, never raw payloads
# ---------------------------------------------------------------------------

def test_bundle_journal_tail_is_redacted(tmp_path):
    journal = str(tmp_path / "journal.jsonl")
    secret_row = [1234.5678, 8765.4321]
    with open(journal, "w") as f:
        f.write(json.dumps({
            "op": "register", "tenant": "acme", "n_perm": 64,
            "matrix": [secret_row, [2.5, 3.5]],
            "note": "q" * 400,
        }) + "\n")
        f.write("not json — torn tail line\n")
    out = bundle.collect(str(tmp_path / "b"), reason="redaction",
                         journal=journal)
    tail = [json.loads(ln)
            for ln in open(os.path.join(out, "journal_tail.jsonl"))]
    assert len(tail) == 1      # the torn line is dropped, not shipped raw
    rec = tail[0]
    # scalars survive; every sequence / oversized string is digest-only
    assert rec["tenant"] == "acme" and rec["n_perm"] == 64
    assert rec["matrix"]["redacted"] == "sequence"
    assert set(rec["matrix"]) == {"redacted", "items", "sha256", "bytes"}
    assert rec["note"]["redacted"] == "text" and rec["note"]["chars"] == 400
    raw = open(os.path.join(out, "journal_tail.jsonl")).read()
    assert "1234.5678" not in raw and "qqqq" not in raw


def test_bundle_env_snapshot_is_filtered(tmp_path, monkeypatch):
    monkeypatch.setenv("NETREP_FLIGHTREC_ENTRIES", "2048")
    monkeypatch.setenv("SECRET_TOKEN", "hunter2")
    out = bundle.collect(str(tmp_path / "envb"), reason="env")
    env = json.load(open(os.path.join(out, "env.json")))
    assert "NETREP_FLIGHTREC_ENTRIES" in env["env"]
    assert "SECRET_TOKEN" not in env["env"]
    assert "hunter2" not in json.dumps(env)


def test_bundle_collision_suffix_never_overwrites(tmp_path):
    a = bundle.collect(str(tmp_path / "dup"), reason="x")
    b = bundle.collect(str(tmp_path / "dup"), reason="x")
    assert a != b and os.path.isdir(a) and os.path.isdir(b)
    assert b.endswith("-2")


def test_render_report_rejects_non_bundle(tmp_path):
    with pytest.raises(ValueError, match="not a diagnostic bundle"):
        bundle.render_report(str(tmp_path))


# ---------------------------------------------------------------------------
# live-server forensics: dump wire op + SIGUSR2
# ---------------------------------------------------------------------------

def test_dump_wire_op_collects_bundle(tmp_path):
    from netrep_tpu.serve import PreservationServer, ServeConfig
    from netrep_tpu.serve.server import dispatch_op

    journal = str(tmp_path / "serve_journal.jsonl")
    with open(journal, "w") as f:
        f.write(json.dumps({"kind": "submit", "payload": [1, 2, 3]}) + "\n")
    server = PreservationServer(
        ServeConfig(journal=journal,
                    telemetry=str(tmp_path / "tel.jsonl")),
        start=False,
    )
    try:
        resp = dispatch_op(
            server,
            {"op": "dump", "dest": str(tmp_path / "wired"), "reason": "wire"},
            threading.Event(),
        )
    finally:
        server.close(drain=False)
    assert resp["ok"] is True
    out = resp["bundle"]
    assert os.path.isdir(out)
    man = json.load(open(os.path.join(out, "manifest.json")))
    assert man["reason"] == "wire"
    # the server's journal rode along, redacted
    tail = [json.loads(ln)
            for ln in open(os.path.join(out, "journal_tail.jsonl"))]
    assert tail and tail[0]["payload"]["redacted"] == "sequence"
    assert "reason=wire" in bundle.render_report(out)


def test_sigusr2_dumps_bundle_on_live_daemon(tmp_path):
    if not hasattr(signal, "SIGUSR2"):
        pytest.skip("no SIGUSR2 on this platform")
    sock = str(tmp_path / "s.sock")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    proc = subprocess.Popen(
        [sys.executable, "-m", "netrep_tpu", "serve",
         "--socket", sock, "--no-journal"],
        cwd=str(tmp_path), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env,
    )
    try:
        deadline = time.monotonic() + 180
        while not os.path.exists(sock):
            assert proc.poll() is None, proc.stderr.read()
            assert time.monotonic() < deadline, "daemon never bound"
            time.sleep(0.1)
        proc.send_signal(signal.SIGUSR2)
        bdir = tmp_path / "netrep-bundle-sigusr2"
        deadline = time.monotonic() + 60
        while not (bdir / "manifest.json").is_file():
            assert proc.poll() is None, proc.stderr.read()
            assert time.monotonic() < deadline, "no bundle after SIGUSR2"
            time.sleep(0.1)
    finally:
        try:
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=60)
        except (subprocess.TimeoutExpired, OSError):
            proc.kill()
            proc.wait()
    man = json.load(open(bdir / "manifest.json"))
    assert man["reason"] == "sigusr2" and man["pid"] == proc.pid
    assert "reason=sigusr2" in bundle.render_report(str(bdir))


# ---------------------------------------------------------------------------
# fleet: the coordinator collects the departed replica's bundle
# ---------------------------------------------------------------------------

def _mk_fleet(tmp_path, n=2):
    from netrep_tpu.serve import (
        FleetConfig, ServeConfig, build_inprocess_fleet,
    )

    def mk(rid, jpath, ckpt):
        return ServeConfig(engine=CFG, journal=jpath, checkpoint_dir=ckpt,
                           fleet_label=rid)

    return build_inprocess_fleet(
        n, str(tmp_path / "fleet"), make_config=mk,
        fleet_config=FleetConfig(
            telemetry=str(tmp_path / "coord.jsonl"), heartbeat_s=0.1,
        ),
    )


def test_fleet_failover_collects_departed_replica_bundle(tmp_path):
    fleet = _mk_fleet(tmp_path)
    try:
        home = fleet.route("a", "d", "t")
        fleet.kill_replica(home.rid)
        assert fleet.await_failover(home.rid, timeout=60)
    finally:
        fleet.close()
    bdir = (tmp_path / "fleet" / "bundles"
            / f"netrep-bundle-replica_failover-{home.rid}")
    assert bdir.is_dir()
    man = json.load(open(bdir / "manifest.json"))
    assert man["reason"] == "replica_failover"
    # the coordinator's own JSONL tells the anomaly story: the scanned
    # replica_lost event fired the replica_failover detector
    evs = [json.loads(ln) for ln in open(tmp_path / "coord.jsonl")]
    anom = [e for e in evs if e["ev"] == "anomaly_detected"
            and e["data"].get("detector") == "replica_failover"]
    assert anom and anom[0]["data"]["replica"] == home.rid
    assert "replica_failover" in bundle.render_report(str(bdir))


def test_fleet_evict_handoff_collects_bundle(tmp_path):
    fleet = _mk_fleet(tmp_path)
    try:
        home = fleet.route("a", "d", "t")
        out = fleet.evict_notice(home.rid, grace_s=1.0)
        assert out is not None
    finally:
        fleet.close()
    bdir = (tmp_path / "fleet" / "bundles"
            / f"netrep-bundle-replica_evicted-{home.rid}")
    assert bdir.is_dir()
    assert json.load(open(bdir / "manifest.json"))["reason"] == \
        "replica_evicted"
    evs = [json.loads(ln) for ln in open(tmp_path / "coord.jsonl")]
    anom = [e for e in evs if e["ev"] == "anomaly_detected"
            and e["data"].get("detector") == "replica_evicted"]
    assert anom and anom[0]["data"]["replica"] == home.rid


# ---------------------------------------------------------------------------
# one-command CLI
# ---------------------------------------------------------------------------

def test_cli_bundle_collect_then_render(tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    dest = str(tmp_path / "clib")
    out = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "bundle",
         "--collect", dest, "--reason", "cli-drill"],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert out.returncode == 0, out.stderr
    assert dest in out.stdout
    rendered = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "bundle", dest],
        capture_output=True, text=True, env=env, timeout=180,
    )
    assert rendered.returncode == 0, rendered.stderr
    assert "reason=cli-drill" in rendered.stdout
    # the collecting process never loaded a backend for forensics
    assert "jax=not-loaded" in rendered.stdout
