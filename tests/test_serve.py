"""`netrep serve` tests (ISSUE 7) — CPU-only, socket-free (in-process
client), tiny fixtures: bit-parity of served results vs direct
``module_preservation()`` calls in fixed-n and adaptive modes, cross-request
(and cross-tenant) dispatch packing, warm-pool compile amortization,
admission control, weighted round-robin fairness, graceful drain, and
pack-level fault isolation."""

import json

import numpy as np
import pytest

import netrep_tpu
from netrep_tpu import module_preservation
from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops import pvalues as pv
from netrep_tpu.serve import (
    InProcessClient, PreservationServer, QueueFull, ServeConfig, ServeError,
)
from netrep_tpu.utils.config import EngineConfig, FaultPolicy

#: the ONE engine config served runs and their direct-call twins share —
#: bit-parity is only defined against the same kernels and chunking
CFG = EngineConfig(chunk_size=16, autotune=False)


@pytest.fixture(scope="module")
def fx():
    """Deterministic fixture pair + the direct-call input dict."""
    mixed = make_mixed_pair(100, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    direct_kw = dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", config=CFG,
    )
    return dict(dn=dn, dc=dc, dd=dd, tn=tn, tc=tc, td=td, assign=assign,
                direct_kw=direct_kw)


def make_server(fx, tmp_path, *, tenants=("a",), start=True, **cfg_kw):
    cfg_kw.setdefault("engine", CFG)
    cfg_kw.setdefault("telemetry", str(tmp_path / "serve_tel.jsonl"))
    srv = PreservationServer(ServeConfig(**cfg_kw), start=start)
    client = InProcessClient(srv)
    for t in tenants:
        client.register_dataset(t, "d", network=fx["dn"],
                                correlation=fx["dc"], data=fx["dd"],
                                assignments=fx["assign"])
        client.register_dataset(t, "t", network=fx["tn"],
                                correlation=fx["tc"], data=fx["td"])
    return srv, client


def read_events(path):
    return [json.loads(l) for l in open(path, encoding="utf-8")]


# ---------------------------------------------------------------------------
# bit-parity (the ISSUE 7 satellite): served == direct, fixed and adaptive
# ---------------------------------------------------------------------------

def test_served_request_bit_identical_fixed(fx, tmp_path):
    srv, client = make_server(fx, tmp_path)
    try:
        res = client.analyze("a", "d", "t", n_perm=64, seed=3, timeout=600)
    finally:
        srv.close()
    direct = module_preservation(**fx["direct_kw"], n_perm=64, seed=3)
    np.testing.assert_array_equal(res["observed"], direct.observed)
    np.testing.assert_array_equal(res["p_values"],
                                  np.asarray(direct.p_values))
    assert res["p_type"] == "fixed" and res["completed"] == 64
    # counts parity: the served tallies equal tail_counts of the direct
    # run's materialized null
    hi, lo, eff = pv.tail_counts(
        direct.observed, np.asarray(direct.nulls)[:direct.completed]
    )
    np.testing.assert_array_equal(res["counts_hi"], hi)
    np.testing.assert_array_equal(res["counts_lo"], lo)
    np.testing.assert_array_equal(res["counts_eff"], eff)
    assert res["module_labels"] == list(direct.module_labels)


def test_served_request_bit_identical_adaptive(fx, tmp_path):
    srv, client = make_server(fx, tmp_path)
    try:
        res = client.analyze("a", "d", "t", n_perm=96, seed=5,
                             adaptive=True, timeout=600)
    finally:
        srv.close()
    direct = module_preservation(**fx["direct_kw"], n_perm=96, seed=5,
                                 adaptive=True)
    np.testing.assert_array_equal(res["p_values"],
                                  np.asarray(direct.p_values))
    np.testing.assert_array_equal(res["n_perm_used"],
                                  np.asarray(direct.n_perm_used))
    assert res["p_type"] == "sequential"


# ---------------------------------------------------------------------------
# cross-request packing (the tentpole): shared dispatches, per-request RNG
# ---------------------------------------------------------------------------

def test_concurrent_requests_share_one_pack_bit_identically(fx, tmp_path):
    """Three queued requests — different seeds, different budgets, one
    adaptive — run as ONE pack (shared module-size-bucket dispatches) and
    each result is bit-identical to its direct call."""
    srv, client = make_server(fx, tmp_path, start=False)
    h1 = client.submit("a", "d", "t", n_perm=64, seed=3)
    h2 = client.submit("a", "d", "t", n_perm=32, seed=11)
    h3 = client.submit("a", "d", "t", n_perm=64, seed=5, adaptive=True)
    srv.start()
    try:
        r1 = client.result(h1, timeout=600)
        r2 = client.result(h2, timeout=600)
        r3 = client.result(h3, timeout=600)
    finally:
        srv.close()
    # pack sizes are canonicalized to powers of two: 3 queued -> 2 + 1
    assert sorted([r1["pack_size"], r2["pack_size"], r3["pack_size"]],
                  reverse=True) == [2, 2, 1]
    assert len({r1["pack_id"], r2["pack_id"], r3["pack_id"]}) == 2
    for res, kw in (
        (r1, dict(n_perm=64, seed=3)),
        (r2, dict(n_perm=32, seed=11)),
        (r3, dict(n_perm=64, seed=5, adaptive=True)),
    ):
        direct = module_preservation(**fx["direct_kw"], **kw)
        np.testing.assert_array_equal(res["observed"], direct.observed)
        np.testing.assert_array_equal(res["p_values"],
                                      np.asarray(direct.p_values))


def test_cross_tenant_packing(fx, tmp_path):
    """Two tenants registering identical data land in one shared dispatch
    (the pack key is the dataset-pair content digest, not the tenant)."""
    srv, client = make_server(fx, tmp_path, tenants=("a", "b"),
                              start=False)
    ha = client.submit("a", "d", "t", n_perm=32, seed=1)
    hb = client.submit("b", "d", "t", n_perm=32, seed=2)
    srv.start()
    try:
        ra = client.result(ha, timeout=600)
        rb = client.result(hb, timeout=600)
    finally:
        srv.close()
    assert ra["pack_id"] == rb["pack_id"] and ra["pack_size"] == 2
    ev = read_events(str(tmp_path / "serve_tel.jsonl"))
    packed = [e for e in ev if e["ev"] == "request_packed"]
    assert {e["data"]["tenant"] for e in packed} == {"a", "b"}
    assert len({e["data"]["pack"] for e in packed}) == 1


def test_multi_test_request_matches_vmap_tests(fx, tmp_path):
    """A request with a LIST of test datasets rides the MultiTestEngine
    T-axis and returns per-test results bit-identical to the direct
    vmap_tests=True call."""
    m2 = make_mixed_pair(100, 3, n_samples=16, seed=9)
    (t2d, t2c, t2n) = m2["test"]
    srv, client = make_server(fx, tmp_path)
    client.register_dataset("a", "t2", network=t2n, correlation=t2c,
                            data=t2d)
    try:
        res = client.analyze("a", "d", ["t", "t2"], n_perm=48, seed=4,
                             timeout=600)
    finally:
        srv.close()
    direct = module_preservation(
        network={"d": fx["dn"], "t": fx["tn"], "t2": t2n},
        correlation={"d": fx["dc"], "t": fx["tc"], "t2": t2c},
        data={"d": fx["dd"], "t": fx["td"], "t2": t2d},
        module_assignments=fx["assign"], discovery="d",
        test=["t", "t2"], n_perm=48, seed=4, config=CFG,
        vmap_tests=True, simplify=False,
    )
    assert [t["test"] for t in res["tests"]] == ["t", "t2"]
    for t in res["tests"]:
        dr = direct["d"][t["test"]]
        np.testing.assert_array_equal(t["observed"], dr.observed)
        np.testing.assert_array_equal(t["p_values"],
                                      np.asarray(dr.p_values))


# ---------------------------------------------------------------------------
# warm program pool: steady-state requests never pay compile
# ---------------------------------------------------------------------------

def test_warm_pool_second_request_pays_no_compile(fx, tmp_path):
    tel = str(tmp_path / "serve_tel.jsonl")
    srv, client = make_server(fx, tmp_path)
    try:
        r1 = client.analyze("a", "d", "t", n_perm=48, seed=1, timeout=600)
        r2 = client.analyze("a", "d", "t", n_perm=48, seed=2, timeout=600)
    finally:
        srv.close()
    assert r1["pool_hit"] is False and r2["pool_hit"] is True
    spans = [e["data"] for e in read_events(tel)
             if e["ev"] == "compile_span" and "packed" in e["data"]["key"]]
    assert len(spans) >= 2
    cold, warm = spans[0]["s"], spans[-1]["s"]
    # the PR 5 proof metric: the warm-pool request's compile estimate
    # collapses (engine + jitted programs reused, zero re-trace)
    assert warm < max(0.5 * cold, 0.05), (cold, warm)
    ev_names = {e["ev"] for e in read_events(tel)}
    assert {"serve_pool_miss", "serve_pool_hit"} <= ev_names


# ---------------------------------------------------------------------------
# admission control + fairness + drain
# ---------------------------------------------------------------------------

def test_admission_control_rejects_over_bound(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, start=False, max_queue=2)
    client.submit("a", "d", "t", n_perm=32, seed=1)
    client.submit("a", "d", "t", n_perm=32, seed=2)
    with pytest.raises(QueueFull):
        client.submit("a", "d", "t", n_perm=32, seed=3)
    ev = read_events(str(tmp_path / "serve_tel.jsonl"))
    rej = [e for e in ev if e["ev"] == "request_rejected"]
    assert rej and rej[0]["data"]["reason"] == "queue_full"
    assert rej[0]["data"]["tenant"] == "a"
    srv.close(drain=False)


def test_weighted_round_robin_order(fx, tmp_path):
    """weight(a)=2, weight(b)=1, packing off: dispatch order follows the
    weighted ring a,a,b,a,a,b."""
    srv, client = make_server(fx, tmp_path, tenants=(), start=False,
                              max_pack=1)
    client.register_tenant("a", weight=2)
    client.register_tenant("b", weight=1)
    for t in ("a", "b"):
        client.register_dataset(t, "d", network=fx["dn"],
                                correlation=fx["dc"], data=fx["dd"],
                                assignments=fx["assign"])
        client.register_dataset(t, "t", network=fx["tn"],
                                correlation=fx["tc"], data=fx["td"])
    handles = []
    for i in range(4):
        handles.append(client.submit("a", "d", "t", n_perm=32, seed=i))
    for i in range(2):
        handles.append(client.submit("b", "d", "t", n_perm=32,
                                     seed=100 + i))
    srv.start()
    try:
        for h in handles:
            client.result(h, timeout=600)
    finally:
        srv.close()
    ev = read_events(str(tmp_path / "serve_tel.jsonl"))
    order = [e["data"]["tenant"] for e in ev
             if e["ev"] == "request_packed"]
    assert order == ["a", "a", "b", "a", "a", "b"]


def test_graceful_drain_finishes_queued_work(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, start=False)
    h1 = client.submit("a", "d", "t", n_perm=32, seed=1)
    h2 = client.submit("a", "d", "t", n_perm=32, seed=2)
    srv.start()
    srv.close(drain=True)   # must finish both queued requests first
    assert client.result(h1, timeout=1)["completed"] == 32
    assert client.result(h2, timeout=1)["completed"] == 32
    ev = read_events(str(tmp_path / "serve_tel.jsonl"))
    end = [e for e in ev if e["ev"] == "serve_end"]
    assert end and end[0]["data"]["drained"] is True
    assert end[0]["data"]["requests_done"] == 2
    # draining servers refuse new work explicitly
    with pytest.raises(ServeError, match="draining"):
        client.submit("a", "d", "t", n_perm=32, seed=3)


# ---------------------------------------------------------------------------
# fault ladder around shared dispatches
# ---------------------------------------------------------------------------

def test_transient_fault_in_pack_recovers_bit_identically(fx, tmp_path):
    srv, client = make_server(
        fx, tmp_path,
        fault_policy=FaultPolicy(plan="transient@8", backoff_base_s=0.0,
                                 backoff_jitter=0.0),
    )
    try:
        res = client.analyze("a", "d", "t", n_perm=64, seed=3, timeout=600)
    finally:
        srv.close()
    direct = module_preservation(**fx["direct_kw"], n_perm=64, seed=3)
    np.testing.assert_array_equal(res["p_values"],
                                  np.asarray(direct.p_values))
    ev = read_events(str(tmp_path / "serve_tel.jsonl"))
    names = [e["ev"] for e in ev]
    assert "fault_injected" in names and "retry_attempt" in names


def test_failed_pack_is_isolated_per_request(fx, tmp_path):
    """An unrecoverable fault inside a shared dispatch must not take the
    pack-mates down with it: the pack splits, each member retries solo,
    the poisoned ones fail alone, and the server keeps serving."""
    srv, client = make_server(
        fx, tmp_path, tenants=("a", "b"), start=False,
        # three fatal firings: the shared pack, then each solo retry —
        # both requests are genuinely poisoned and fail individually
        fault_policy=FaultPolicy(plan="fatal@8x3", backoff_base_s=0.0,
                                 backoff_jitter=0.0),
    )
    ha = client.submit("a", "d", "t", n_perm=32, seed=1)
    hb = client.submit("b", "d", "t", n_perm=32, seed=2)
    srv.start()
    with pytest.raises(ServeError):
        client.result(ha, timeout=600)
    with pytest.raises(ServeError):
        client.result(hb, timeout=600)
    # the plan is exhausted; the SERVER is alive and the next request of
    # either tenant succeeds — one pack's death never drains the service
    res = client.analyze("b", "d", "t", n_perm=32, seed=9, timeout=600)
    direct = module_preservation(**fx["direct_kw"], n_perm=32, seed=9)
    np.testing.assert_array_equal(res["p_values"],
                                  np.asarray(direct.p_values))
    st = srv.stats()
    srv.close()
    assert st["tenants"]["a"]["failed"] == 1
    assert st["tenants"]["b"]["failed"] == 1
    assert st["tenants"]["b"]["done"] == 1
    ev = read_events(str(tmp_path / "serve_tel.jsonl"))
    assert any(e["ev"] == "request_requeued" for e in ev)


# ---------------------------------------------------------------------------
# ops surface
# ---------------------------------------------------------------------------

def test_metrics_exposition_and_stats(fx, tmp_path):
    srv, client = make_server(fx, tmp_path)
    try:
        client.analyze("a", "d", "t", n_perm=32, seed=1, timeout=600)
        text = client.metrics()
        st = client.stats()
    finally:
        srv.close()
    assert 'netrep_serve_requests_total{tenant="a",outcome="done"} 1' in text
    assert 'netrep_serve_queue_depth{tenant="a"} 0' in text
    assert "netrep_serve_packs_total" in text
    # the engine-run registry rides the same exposition (shared bus)
    assert "netrep_chunk_count_total" in text
    assert st["tenants"]["a"]["done"] == 1 and st["packs"] >= 1


def test_data_only_register_and_analyze_parity(fx, tmp_path):
    """ISSUE 9 satellite: `register_dataset` accepts the data-only atlas
    payload (data + beta, no correlation/network); the served analysis is
    bit-identical to the direct data-only call; the content digest covers
    the derivation params, so a different β is a different identity."""
    beta = 2.0
    srv = PreservationServer(ServeConfig(
        engine=CFG, telemetry=str(tmp_path / "tel.jsonl")
    ))
    client = InProcessClient(srv)
    try:
        d1 = client.register_dataset("a", "d", data=fx["dd"], beta=beta,
                                     assignments=fx["assign"])
        d2 = client.register_dataset("a", "t", data=fx["td"], beta=beta)
        # derivation params ride the digest: same data, different β →
        # different identity (never shares a pack / pooled engine)
        d1b = client.register_dataset("a", "d3", data=fx["dd"],
                                      beta=(3.0, "signed"),
                                      assignments=fx["assign"])
        assert d1.endswith("|beta:2|unsigned")
        assert d1b.endswith("|beta:3|signed")
        assert d1.split("|")[0] == d1b.split("|")[0]  # same data content
        assert d1 != d2
        res = client.analyze("a", "d", "t", n_perm=64, seed=3,
                             timeout=600)
    finally:
        srv.close()
    direct = netrep_tpu.atlas_module_preservation(
        {"d": fx["dd"], "t": fx["td"]},
        module_assignments={"d": fx["assign"]}, data_only=beta,
        discovery="d", test="t", n_perm=64, seed=3, config=CFG,
    )
    np.testing.assert_array_equal(res["observed"], direct.observed)
    np.testing.assert_array_equal(res["p_values"],
                                  np.asarray(direct.p_values))
    hi, lo, eff = pv.tail_counts(
        direct.observed, np.asarray(direct.nulls)[:direct.completed]
    )
    np.testing.assert_array_equal(res["counts_hi"], hi)
    np.testing.assert_array_equal(res["counts_lo"], lo)


def test_data_only_register_validation(fx, tmp_path):
    srv = PreservationServer(ServeConfig(engine=CFG), start=False)
    client = InProcessClient(srv)
    try:
        with pytest.raises(ServeError, match="network\\+correlation"):
            client.register_dataset("a", "d", data=fx["dd"])  # no beta
        with pytest.raises(ServeError, match="must not pass"):
            client.register_dataset("a", "d", network=fx["dn"],
                                    correlation=fx["dc"], beta=2.0)
        client.register_dataset("a", "d", data=fx["dd"], beta=2.0,
                                assignments=fx["assign"])
        client.register_dataset("a", "dense_t", network=fx["tn"],
                                correlation=fx["tc"], data=fx["td"])
        client.register_dataset("a", "t", data=fx["td"], beta=3.0)
        # mixing a data-only side with a dense one — or two different
        # derivations — fails fast at submit
        with pytest.raises(ServeError, match="cannot mix"):
            client.submit("a", "d", "dense_t", n_perm=16)
        with pytest.raises(ServeError, match="different derivation"):
            client.submit("a", "d", "t", n_perm=16)
    finally:
        srv.close(drain=False)


def test_unknown_tenant_and_dataset_fail_fast(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, start=False)
    with pytest.raises(ServeError, match="unknown tenant"):
        client.submit("ghost", "d", "t", n_perm=16)
    with pytest.raises(ServeError, match="no dataset"):
        client.submit("a", "d", "nope", n_perm=16)
    srv.close(drain=False)
