"""Unified run telemetry (ISSUE 3): event-schema stability, registry
folding, NullProfile parity of the aggregated JSONL, bit-identical
disabled runs, the stall watchdog's fake-clock semantics, retirement /
checkpoint events, the CLI report, and the logging-hygiene guard."""

import glob
import json
import logging
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from netrep_tpu.data import make_mixed_pair
from netrep_tpu.ops.sequential import StopMonitor, StopRule
from netrep_tpu.parallel.engine import ModuleSpec, PermutationEngine
from netrep_tpu.utils.config import EngineConfig
from netrep_tpu.utils.profiling import NullProfile
from netrep_tpu.utils.telemetry import (
    EVENT_KEYS, SCHEMA_VERSION, MetricsRegistry, StallWatchdog, Telemetry,
    aggregate_file, current, read_events,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = EngineConfig(chunk_size=32, summary_method="eigh", superchunk=2,
                   autotune=False)
N_PERM = 96


@pytest.fixture(scope="module")
def mixed():
    return make_mixed_pair(200, 4, n_samples=24, seed=7)


def _engine(mixed, config=CFG):
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    specs = [ModuleSpec(lab, idx, idx) for lab, idx in mixed["specs"]]
    return PermutationEngine(
        dc, dn, dd, tc, tn, td, specs, mixed["pool"], config=config
    )


# ---------------------------------------------------------------------------
# schema stability (golden event shape, versioned constant)
# ---------------------------------------------------------------------------

def test_event_schema_golden(tmp_path):
    """Every emitted line has EXACTLY the six schema keys, in order, with
    the pinned version — downstream parsers (summarize_watch, dashboards)
    key on this shape, so a drift must fail CI, not them."""
    assert SCHEMA_VERSION == 1  # bump deliberately, with this test
    assert EVENT_KEYS == ("v", "t", "m", "run", "ev", "data")
    path = tmp_path / "ev.jsonl"
    tel = Telemetry(path, run_id="golden")
    tel.emit("chunk", done=32, total=96, take=32, s=0.5, dispatches=2,
             host_bytes=1024)
    tel.emit("stall_suspected", elapsed_s=99.0, steady_chunk_s=1.0,
             factor=10.0, chunks_done=3)
    tel.emit("checkpoint_saved", path="x.npz", completed=64, bytes=100)
    tel.emit("numpy_payload", arr=np.arange(3), scalar=np.int64(7))
    tel.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 4
    for row in lines:
        assert tuple(row.keys()) == EVENT_KEYS
        assert row["v"] == SCHEMA_VERSION
        assert row["run"] == "golden"
        assert isinstance(row["ev"], str)
        assert isinstance(row["data"], dict)
        assert isinstance(row["t"], float) and isinstance(row["m"], float)
    # numpy values serialize as plain JSON numbers/lists
    assert lines[3]["data"] == {"arr": [0, 1, 2], "scalar": 7}


def test_registry_fold_rules_and_renders():
    reg = MetricsRegistry()
    reg.fold("chunk", {"s": 1.0, "dispatches": 2, "done": 32}, t=10.0,
             run="r1")
    reg.fold("chunk", {"s": 3.0, "dispatches": 2, "done": 64}, t=12.0,
             run="r1")
    assert reg.counters["chunk.count"] == 2
    assert reg.counters["chunk.dispatches"] == 4      # sum field
    assert reg.gauges["chunk.done"] == 64             # last value
    assert reg.histograms["chunk.s"] == [2, 4.0, 1.0, 3.0]
    assert reg.runs == {"r1"} and reg.n_events == 2
    table = reg.render_summary()
    assert "chunk.dispatches" in table and "chunk.s" in table
    prom = reg.render_prometheus()
    assert "# TYPE netrep_chunk_dispatches_total counter" in prom
    assert "netrep_chunk_s_sum 4" in prom
    assert "# TYPE netrep_chunk_done gauge" in prom


# ---------------------------------------------------------------------------
# acceptance: streaming run's JSONL reproduces NullProfile exactly;
# disabled telemetry is bit-identical
# ---------------------------------------------------------------------------

def test_streaming_telemetry_reproduces_nullprofile(mixed, tmp_path):
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    path = tmp_path / "stream.jsonl"
    tel = Telemetry(path, run_id="stream")
    prof = NullProfile()
    ck = str(tmp_path / "ck.npz")
    with tel.activate():  # ambient: checkpoint events must fire too
        sc = eng.run_null_streaming(
            N_PERM, observed, key=0, profile=prof, checkpoint_path=ck,
            checkpoint_every=32,
        )
    tel.close()
    assert sc.completed == N_PERM
    reg = aggregate_file(str(path))
    # the emitted event stream carries NullProfile's accounting exactly
    assert reg.counters["superchunk.dispatches"] == prof.dispatches
    assert reg.counters["superchunk.host_bytes"] == prof.host_bytes
    assert reg.counters["superchunk.perms"] == N_PERM
    assert reg.counters["null_run_end.dispatches"] == prof.dispatches
    assert reg.counters["null_run_end.host_bytes"] == prof.host_bytes
    assert reg.counters["checkpoint_saved.count"] >= 1
    # aggregated == live registry (one fold rule, two views)
    assert reg.counters["superchunk.dispatches"] == \
        tel.metrics.counters["superchunk.dispatches"]

    # resume-complete run on the same checkpoint: the shared identity
    # validation emits the resume event
    tel2 = Telemetry(tmp_path / "resume.jsonl", run_id="resume")
    with tel2.activate():
        sc2 = eng.run_null_streaming(
            N_PERM, observed, key=0, checkpoint_path=ck,
        )
    tel2.close()
    assert sc2.completed == N_PERM
    assert (sc2.hi == sc.hi).all()
    reg2 = aggregate_file(str(tmp_path / "resume.jsonl"))
    assert reg2.counters["checkpoint_resumed.count"] == 1
    assert reg2.gauges["checkpoint_resumed.completed"] == N_PERM


def test_disabled_telemetry_bit_identical(mixed, tmp_path):
    eng = _engine(mixed)
    observed = np.asarray(eng.observed())
    tel = Telemetry(tmp_path / "on.jsonl")
    nulls_on, done_on = eng.run_null(N_PERM, key=0, telemetry=tel)
    sc_on = eng.run_null_streaming(N_PERM, observed, key=0, telemetry=tel)
    tel.close()
    eng_off = _engine(mixed)
    nulls_off, done_off = eng_off.run_null(N_PERM, key=0)
    sc_off = eng_off.run_null_streaming(N_PERM, observed, key=0)
    assert done_on == done_off
    np.testing.assert_array_equal(np.asarray(nulls_on),
                                  np.asarray(nulls_off))
    assert (sc_on.hi == sc_off.hi).all() and (sc_on.lo == sc_off.lo).all()
    assert (sc_on.eff == sc_off.eff).all()
    # no USER bus leaked out of the runs — only the always-on flight bus
    # (ISSUE 20) may remain ambient
    assert current() is None or getattr(current(), "flight_only", False)


def test_materialized_chunk_events_match_profile(mixed, tmp_path):
    eng = _engine(mixed)
    path = tmp_path / "mat.jsonl"
    tel = Telemetry(path)
    prof = NullProfile()
    nulls, done = eng.run_null(N_PERM, key=0, telemetry=tel, profile=prof)
    tel.close()
    assert done == N_PERM
    reg = aggregate_file(str(path))
    assert reg.counters["chunk.count"] == N_PERM // CFG.chunk_size
    assert reg.counters["chunk.take"] == N_PERM
    assert reg.counters["chunk.dispatches"] == prof.dispatches
    assert reg.counters["chunk.host_bytes"] == prof.host_bytes


# ---------------------------------------------------------------------------
# stall watchdog (fake clock — no sleeping, no thread)
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_watchdog_fires_on_stall_and_stays_silent_otherwise(caplog):
    clock = FakeClock()
    tel = Telemetry(clock=clock)  # registry-only bus
    wd = StallWatchdog(tel, factor=5.0, poll_interval=0, clock=clock)
    wd.arm()
    clock.t = 10.0
    wd.beat()                       # first chunk: includes compile
    for _ in range(4):              # steady state: 1 s / chunk
        clock.t += 1.0
        wd.beat()
    assert wd.steady_s() == 1.0     # compile interval excluded
    clock.t += 2.0                  # 2 s < 5x steady: normal jitter
    assert not wd.poll()
    assert "stall_suspected.count" not in tel.metrics.counters
    with caplog.at_level(logging.WARNING, logger="netrep_tpu"):
        clock.t += 10.0             # 12 s > 5x steady: stall
        assert wd.poll()
        assert wd.poll() is False   # one event per stall episode
    assert tel.metrics.counters["stall_suspected.count"] == 1
    assert tel.metrics.gauges["stall_suspected.chunks_done"] == 5
    warns = [r for r in caplog.records if "stalled" in r.getMessage()]
    assert len(warns) == 1          # warns ONCE per stall episode
    events = []
    tel.subscribe(events.append)
    clock.t += 1.0
    wd.beat()                       # recovery: emits stall_recovered + re-arms
    assert tel.metrics.counters["stall_recovered.count"] == 1
    # the event carries how long the run was stalled (keys pinned)
    rec = [e for e in events if e["ev"] == "stall_recovered"]
    assert set(rec[0]["data"]) == {"stalled_s", "chunks_done"}
    assert rec[0]["data"]["stalled_s"] > 10.0
    with caplog.at_level(logging.WARNING, logger="netrep_tpu"):
        clock.t += 50.0
        assert wd.poll()            # a second stall fires again
    assert tel.metrics.counters["stall_suspected.count"] == 2
    warns = [r for r in caplog.records if "stalled" in r.getMessage()]
    assert len(warns) == 2          # re-armed: the second stall warns too


def test_watchdog_silent_before_steady_state_measured():
    clock = FakeClock()
    tel = Telemetry(clock=clock)
    wd = StallWatchdog(tel, factor=2.0, min_intervals=2, poll_interval=0,
                       clock=clock)
    wd.arm()
    clock.t = 1000.0                # huge gap, but no baseline yet
    assert not wd.poll()
    wd.beat()
    clock.t += 1.0
    wd.beat()                       # only ONE steady interval so far
    clock.t += 1000.0
    assert not wd.poll()            # still below min_intervals


def test_watchdog_action_refires_across_stall_episodes():
    """ISSUE 20 satellite: the interval that ends a FIRED stall episode
    must NOT fold into the steady-state median. Before the fix, each
    recovery beat appended the stalled duration to the interval list;
    once the stalled values reached the upper-middle of the sorted list
    the median silently jumped to the stalled duration, and the next
    comparable stall never crossed factor × steady — with a customized
    ``action_factor``, the re-armed warning AND the action callback went
    permanently quiet mid-run."""
    clock = FakeClock()
    tel = Telemetry(clock=clock)    # registry-only bus
    acted = []
    wd = StallWatchdog(tel, factor=2.0, min_intervals=2, poll_interval=0,
                       clock=clock, action=lambda: acted.append(clock.t),
                       action_factor=4.0)
    wd.arm()
    clock.t = 1.0
    wd.beat()                       # first chunk: includes compile
    for _ in range(2):              # steady state: 2 s / chunk
        clock.t += 2.0
        wd.beat()
    assert wd.steady_s() == 2.0
    for episode in range(1, 4):
        clock.t += 26.0             # 26 s > action_factor(4) x 2 s steady
        assert wd.poll(), f"episode {episode} went silent"
        assert len(acted) == episode, f"episode {episode} never acted"
        wd.beat()                   # recovery: re-arms warning + action
        # the stalled interval is excluded from the steady-state samples
        assert wd.steady_s() == 2.0
    assert tel.metrics.counters["stall_suspected.count"] == 3
    assert tel.metrics.counters["stall_recovered.count"] == 3
    # the escalation rides the pinned detector registry (ISSUE 20)
    assert tel.metrics.counters["anomaly_detected.count"] == 3
    assert tel.metrics.gauges["anomaly_detected.action_factor"] == 4.0


def test_recovery_event_names_pinned():
    """ISSUE 4 hygiene: the recovery-path event names are schema surface —
    the CLI recovery section/timeline and downstream dashboards key on
    them, so a rename must fail CI here, deliberately."""
    from netrep_tpu.utils.telemetry import RECOVERY_EVENTS

    assert RECOVERY_EVENTS == (
        "fault_injected",
        "retry_attempt",
        "chunk_abandoned",
        "stall_suspected",
        "stall_recovered",
        "device_lost",
        "mesh_shrunk",
        "mesh_grown",
        "degraded_to_cpu",
        "checkpoint_async_flush",
        "fingerprint_degraded_accept",
        "backend_fallback",
        "distributed_autodetect_failed",
    )


def test_serve_event_names_pinned():
    """ISSUE 7 hygiene: the serving-path request-lifecycle event names are
    schema surface — the CLI per-tenant section and serving dashboards
    key on them (each event carries a ``tenant`` data label; the schema's
    six top-level keys are unchanged)."""
    from netrep_tpu.utils.telemetry import SERVE_EVENTS

    assert SERVE_EVENTS == (
        "request_received",
        "request_packed",
        "request_done",
        "request_rejected",
        # crash-safe serving (ISSUE 10): deadline misses, idempotency
        # dedup, brownout shedding, journal replay, wire hardening
        "request_expired",
        "request_deduped",
        "serve_brownout_enter",
        "serve_brownout_exit",
        "journal_replayed",
        "request_malformed",
        # deadline-driven retirement re-bucketing (ISSUE 10), registered
        # by ISSUE 12's telemetry-registry lint rule
        "request_requeued",
        # per-request deterministic cost attribution (ISSUE 13): carries
        # tenant + trace labels and the conservation-contract fields
        # device_s/transfer_s/perms/bytes_to_host/compile_s_amortized
        "request_cost",
    )


def test_fleet_event_names_pinned():
    """ISSUE 14 hygiene: the fleet-serving event names are schema
    surface — the ``--recovery`` timeline, the per-replica CLI section,
    ``chaos --fleet``, and fleet dashboards key on them (each event
    carries a ``replica`` data label)."""
    from netrep_tpu.utils.telemetry import FLEET_EVENTS

    assert FLEET_EVENTS == (
        "replica_joined",
        "replica_lost",
        "journal_shipped",
        "failover_start",
        "failover_done",
        "ring_rebalanced",
        # replica lifecycle + autoscaling (ISSUE 19): the state machine
        # emits one replica_state per transition; the autoscaler's
        # decisions, the scale-to-zero checkpoint, spawn-on-demand, and
        # the noticed-eviction handoff pair are all first-class names
        "replica_state",
        "autoscale_up",
        "autoscale_down",
        "scale_to_zero",
        "spawn_on_demand",
        "evict_notice",
        "evict_handoff_done",
    )


def test_replica_summary_folds_fleet_events():
    """The per-replica offline aggregation (`telemetry` CLI section):
    joins, losses, shipped records/bytes, and failover count + total
    measured seconds, keyed on the ``replica`` label."""
    from netrep_tpu.utils.telemetry import replica_summary

    def ev(name, **data):
        return {"v": 1, "t": 0.0, "m": 0.0, "run": "x", "ev": name,
                "data": data}

    events = [
        ev("replica_joined", replica="r0"),
        ev("replica_joined", replica="r1"),
        ev("journal_shipped", replica="r0", records=3, bytes=120),
        ev("journal_shipped", replica="r0", records=2, bytes=80),
        ev("replica_lost", replica="r0", peer="r1"),
        ev("failover_start", replica="r0", peer="r1"),
        ev("failover_done", replica="r0", peer="r1", s=0.25, requeued=2),
        ev("request_done", tenant="a", s=1.0),   # not a fleet event
        # lifecycle + eviction fold (ISSUE 19): the LAST replica_state
        # wins (state/gen), evict_notice counts, evict_handoff_done
        # accumulates its measured seconds
        ev("replica_state", replica="r1", prev="spawning", to="ready",
           gen=0, reason="joined"),
        ev("replica_state", replica="r1", prev="ready", to="draining",
           gen=0, reason="evict"),
        ev("evict_notice", replica="r1", grace_s=30.0),
        ev("evict_handoff_done", replica="r1", peer="r0", s=0.5,
           requeued=1, results=2),
    ]
    rows = replica_summary(events)
    assert set(rows) == {"r0", "r1"}
    assert rows["r0"]["shipped_records"] == 5
    assert rows["r0"]["shipped_bytes"] == 200
    assert rows["r0"]["lost"] == 1 and rows["r0"]["failovers"] == 1
    assert rows["r0"]["failover_s"] == pytest.approx(0.25)
    assert rows["r1"]["joined"] == 1 and rows["r1"]["failovers"] == 0
    assert rows["r1"]["state"] == "draining" and rows["r1"]["gen"] == 0
    assert rows["r1"]["evictions"] == 1
    assert rows["r1"]["handoff_s"] == pytest.approx(0.5)
    assert rows["r0"]["evictions"] == 0 and rows["r0"]["state"] is None


def test_grid_events_registered():
    """ISSUE 17: the all-pairs grid events are a pinned registry (the
    lint rule and the CLI grid section both key off these names)."""
    from netrep_tpu.utils.telemetry import GRID_EVENTS, KNOWN_EVENTS

    assert GRID_EVENTS == (
        "grid_start",
        "grid_end",
        "grid_cell_start",
        "grid_cell_done",
        "grid_dedup_hit",
        "grid_warmstart_seeded",
    )
    assert set(GRID_EVENTS) <= KNOWN_EVENTS


def test_grid_summary_folds_grid_events():
    """The all-pairs grid offline aggregation (`telemetry` CLI section):
    per-discovery-row cell outcomes (computed vs manifest, warm starts,
    permutations), plus grid-level dedup hits and wall time."""
    from netrep_tpu.utils.telemetry import grid_summary

    def ev(name, **data):
        return {"v": 1, "t": 0.0, "m": 0.0, "run": "x", "ev": name,
                "data": data}

    events = [
        ev("grid_start", span="s1", datasets=3, cells=4),
        ev("grid_cell_start", discovery="a", test="c", pack_size=2),
        ev("grid_warmstart_seeded", discovery="a", test="c",
           prior_perms=40),
        ev("grid_cell_done", discovery="a", test="c", source="computed",
           perms=64, warmstarted=True),
        ev("grid_cell_done", discovery="a", test="b", source="manifest",
           perms=0),
        ev("grid_cell_start", discovery="b", test="c", pack_size=2),
        ev("grid_cell_done", discovery="b", test="c", source="computed",
           perms=48),
        ev("grid_dedup_hit", kind="props"),
        ev("grid_dedup_hit", kind="observed"),
        ev("grid_end", span="s1", s=1.5, cells_computed=2),
        ev("request_done", tenant="a", s=1.0),   # not a grid event
    ]
    s = grid_summary(events)
    assert s["grids"] == 1 and s["dedup_hits"] == 2
    assert s["wall_s"] == pytest.approx(1.5)
    assert set(s["rows"]) == {"a", "b"}
    a = s["rows"]["a"]
    assert a["started"] == 1 and a["computed"] == 1
    assert a["manifest"] == 1 and a["warmstarted"] == 1
    assert a["perms"] == 64 and a["prior_perms"] == 40
    assert s["rows"]["b"]["computed"] == 1
    assert s["rows"]["b"]["perms"] == 48


def test_histogram_bucket_boundaries_pinned():
    """ISSUE 13: the per-tenant latency/cost histogram boundaries are
    exposition schema — re-binning breaks every dashboard quantile keyed
    on the ``le`` labels, so a change must fail CI here, deliberately."""
    from netrep_tpu.utils.telemetry import COST_BUCKETS_S, LATENCY_BUCKETS_S

    assert LATENCY_BUCKETS_S == (
        0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
        30.0, 60.0, 120.0,
    )
    assert COST_BUCKETS_S == (
        0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0,
    )


def test_bucket_histogram_observe_quantile_and_prom_lines():
    from netrep_tpu.utils.telemetry import BucketHistogram

    h = BucketHistogram((0.1, 1.0, 10.0))
    assert h.quantile(0.5) is None
    for v in (0.05, 0.5, 0.5, 2.0):
        h.observe(v)
    assert h.counts == [1, 2, 1, 0] and h.n == 4
    assert h.total == pytest.approx(3.05)
    # p50 interpolates inside the (0.1, 1.0] bucket
    assert 0.1 <= h.quantile(0.5) <= 1.0
    # +Inf overflow degrades to the last finite boundary
    h2 = BucketHistogram((0.1,))
    h2.observe(5.0)
    assert h2.quantile(0.99) == 0.1
    lines = h.prom_lines("x_seconds", 'tenant="a"')
    assert lines == [
        'x_seconds_bucket{tenant="a",le="0.1"} 1',
        'x_seconds_bucket{tenant="a",le="1"} 3',
        'x_seconds_bucket{tenant="a",le="10"} 4',
        'x_seconds_bucket{tenant="a",le="+Inf"} 4',
        'x_seconds_count{tenant="a"} 4',
        'x_seconds_sum{tenant="a"} 3.05',
    ]


def test_known_events_cover_every_emitted_name():
    """ISSUE 12: the pinned registries (ENGINE/RECOVERY/SERVE/SPAN) are
    the COMPLETE event-name schema. The static half of this contract is
    the ``telemetry-registry`` lint rule; this dynamic half pins the
    union's composition so a registry refactor cannot silently drop a
    subset out of :data:`KNOWN_EVENTS`."""
    from netrep_tpu.utils.telemetry import (
        ENGINE_EVENTS, FLEET_EVENTS, FORENSIC_EVENTS, GRID_EVENTS,
        KNOWN_EVENTS, RECOVERY_EVENTS, SERVE_EVENTS, SPAN_EVENTS,
    )

    union = (ENGINE_EVENTS + RECOVERY_EVENTS + SERVE_EVENTS
             + FLEET_EVENTS + SPAN_EVENTS + GRID_EVENTS
             + FORENSIC_EVENTS)
    # the forensic registry (ISSUE 20) is pinned: these exact names
    assert FORENSIC_EVENTS == ("anomaly_detected", "flightrec_dump",
                               "bundle_written")
    assert KNOWN_EVENTS == frozenset(union)
    # no duplicates across registries: each name has one owning registry
    assert len(union) == len(set(union))
    # spans pair up: every *_start has its *_end in the registry
    for name in SPAN_EVENTS:
        if name.endswith("_start"):
            assert name[:-6] + "_end" in SPAN_EVENTS


def test_tenant_summary_folds_serve_events():
    """The per-tenant offline aggregation (`telemetry` CLI section) counts
    request outcomes, latency stats, and served permutations per tenant
    from the event stream alone."""
    from netrep_tpu.utils.telemetry import render_tenants, tenant_summary

    def ev(name, **data):
        return {"v": 1, "t": 0.0, "m": 0.0, "run": "x", "ev": name,
                "data": data}

    events = [
        ev("request_received", tenant="a"),
        ev("request_packed", tenant="a", pack="p1"),
        ev("request_done", tenant="a", ok=True, s=0.5, perms=128),
        ev("request_received", tenant="b"),
        ev("request_rejected", tenant="b", reason="queue_full"),
        ev("request_done", tenant="b", ok=False, s=1.5, error="Boom"),
        ev("request_expired", tenant="b", miss_s=0.2),
        ev("request_deduped", tenant="a", state="completed"),
        ev("request_cost", tenant="a", device_s=0.25, perms=128,
           bytes_to_host=4096),
        ev("chunk", done=3),           # non-serve events are ignored
        ev("request_done", s=0.1),     # no tenant label: skipped
    ]
    rows = tenant_summary(events)
    assert rows["a"] == {
        "received": 1, "packed": 1, "done": 1, "failed": 0, "rejected": 0,
        "expired": 0, "deduped": 1, "perms": 128,
        "latency": [1, 0.5, 0.5, 0.5],
        "device_s": 0.25, "cost_bytes": 4096,
    }
    assert rows["b"]["rejected"] == 1 and rows["b"]["failed"] == 1
    assert rows["b"]["expired"] == 1
    assert rows["b"]["device_s"] == 0.0
    # the rendered section names both tenants (smoke the CLI surface)
    import json

    path = None
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".jsonl",
                                     delete=False) as f:
        path = f.name
        for e in events:
            f.write(json.dumps(e) + "\n")
    try:
        text = render_tenants(path)
        assert "tenants:" in text and "a" in text and "b" in text
    finally:
        import os

        os.unlink(path)


# ---------------------------------------------------------------------------
# retirement events (StopMonitor owns the tallies, so it emits)
# ---------------------------------------------------------------------------

def test_stop_monitor_emits_module_retired():
    # both modules clearly null (nulls always exceed the observed 0): the
    # Besag-Clifford h rule decides each at the min_perms floor
    rule = StopRule(h=4, alpha=0.05, min_perms=8)
    obs = np.zeros((2, 3))
    events = []
    tel = Telemetry(run_id="ret")
    tel.subscribe(events.append)
    mon = StopMonitor(obs, "greater", rule)
    mon.telemetry = tel
    newly = mon.update(np.full((8, 2, 3), 1.0), 8)
    assert newly.size == 2 and not mon.any_active()
    retired = [e for e in events if e["ev"] == "module_retired"]
    assert len(retired) == 2
    assert tel.metrics.counters["module_retired.count"] == 2
    for e in retired:
        assert e["data"]["n_perm_used"] == 8
        assert e["data"]["hi"] == [8, 8, 8]
        assert len(e["data"]["lo"]) == 3
    # no bus attached: identical decisions, zero emission machinery
    mon2 = StopMonitor(obs, "greater", rule)
    assert mon2.update(np.full((8, 2, 3), 1.0), 8).size == 2


# ---------------------------------------------------------------------------
# public API threading (module_preservation telemetry= + profile pointer)
# ---------------------------------------------------------------------------

def test_module_preservation_telemetry(toy_pair_module, tmp_path):
    pytest.importorskip("pandas")
    from netrep_tpu import module_preservation
    from netrep_tpu.data import pair_frames

    d, t = pair_frames(toy_pair_module)
    path = str(tmp_path / "run.jsonl")
    res = module_preservation(
        network={"d": d["network"], "t": t["network"]},
        correlation={"d": d["correlation"], "t": t["correlation"]},
        data={"d": d["data"], "t": t["data"]},
        module_assignments=dict(toy_pair_module["labels"]),
        discovery="d", test="t", n_perm=64, seed=0,
        config=EngineConfig(chunk_size=32), telemetry=path,
    )
    assert res.profile is not None
    assert res.profile["telemetry"] == path
    reg = aggregate_file(path)
    for ev in ("run_start", "pair_start", "observed", "chunk",
               "null_run_end", "pair_end", "run_end"):
        assert reg.counters.get(f"{ev}.count", 0) >= 1, ev
    assert reg.counters["chunk.take"] == 64
    # user bus deactivated and closed — only the always-on flight bus
    # (ISSUE 20) may remain ambient
    assert current() is None or getattr(current(), "flight_only", False)


# ---------------------------------------------------------------------------
# CLI report
# ---------------------------------------------------------------------------

def test_cli_telemetry_report(tmp_path):
    path = tmp_path / "cli.jsonl"
    tel = Telemetry(path, run_id="cli")
    tel.emit("chunk", done=10, total=10, take=10, s=0.25, dispatches=2,
             host_bytes=64)
    tel.close()
    # a non-event line interleaved (bench metric row): must be skipped
    with open(path, "a") as f:
        f.write('{"metric": "north", "value": 1.0}\nnot json\n')
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    run = lambda *a: subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "telemetry", str(path), *a],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    proc = run()
    assert proc.returncode == 0, proc.stderr
    assert "chunk.dispatches" in proc.stdout and "cli" in proc.stdout
    prom = run("--prom")
    assert prom.returncode == 0
    assert "# TYPE netrep_chunk_dispatches_total counter" in prom.stdout
    js = run("--json")
    row = json.loads(js.stdout)
    assert row["counters"]["chunk.host_bytes"] == 64
    missing = subprocess.run(
        [sys.executable, "-m", "netrep_tpu", "telemetry",
         str(tmp_path / "nope.jsonl")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120,
    )
    assert missing.returncode == 1


def test_read_events_skips_foreign_lines(tmp_path):
    path = tmp_path / "mixed.jsonl"
    tel = Telemetry(path, run_id="r")
    tel.emit("a", s=1.0)
    tel.close()
    with open(path, "a") as f:
        f.write('{"v": 99, "ev": "a", "data": {}}\n')   # wrong version
        f.write('{"metric": "row"}\n--- header ---\n')
    assert len(list(read_events(str(path)))) == 1


# ---------------------------------------------------------------------------
# hygiene: one logger namespace, no import-time basicConfig
# ---------------------------------------------------------------------------

def test_logging_hygiene_across_package():
    """Every module logs via the `netrep_tpu` logger namespace (so one
    handler/config governs the whole package) and nothing calls
    logging.basicConfig at import time (a library must never hijack the
    host application's root logger)."""
    files = glob.glob(os.path.join(REPO, "netrep_tpu", "**", "*.py"),
                      recursive=True)
    assert files
    get_logger = re.compile(r"logging\.getLogger\(([^)]*)\)")
    for path in files:
        with open(path, encoding="utf-8") as f:
            src = f.read()
        assert "basicConfig" not in src, f"{path} touches basicConfig"
        for m in get_logger.finditer(src):
            assert m.group(1) in ('"netrep_tpu"', "'netrep_tpu'"), (
                f"{path} logs outside the netrep_tpu namespace: "
                f"{m.group(0)}"
            )
