"""Packaging metadata stays truthful: version parity with the package,
package discovery finds every subpackage, and the native source ships as
package data (the lazy first-use build depends on it being installed)."""

import os

import pytest

tomllib = pytest.importorskip("tomllib")  # stdlib from 3.11; skip on 3.10

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _project():
    with open(os.path.join(ROOT, "pyproject.toml"), "rb") as f:
        return tomllib.load(f)


def test_version_parity():
    import netrep_tpu

    assert _project()["project"]["version"] == netrep_tpu.__version__


def test_all_subpackages_discovered():
    from setuptools import find_packages

    found = set(find_packages(where=ROOT, include=["netrep_tpu*"]))
    on_disk = {"netrep_tpu"} | {
        f"netrep_tpu.{d}"
        for d in os.listdir(os.path.join(ROOT, "netrep_tpu"))
        if os.path.isdir(os.path.join(ROOT, "netrep_tpu", d))
        and os.path.exists(os.path.join(ROOT, "netrep_tpu", d, "__init__.py"))
    }
    assert found == on_disk, (found, on_disk)


def test_native_source_is_package_data():
    data = _project()["tool"]["setuptools"]["package-data"]
    assert "*.cpp" in data["netrep_tpu.native"]
    assert os.path.exists(
        os.path.join(ROOT, "netrep_tpu", "native", "netstats.cpp")
    )


def test_declared_dependencies_cover_package_imports():
    """Hard dependencies must cover everything the core package imports at
    module scope (plot/pandas extras excluded by design)."""
    deps = {
        d.split(">=")[0].split("==")[0].strip()
        for d in _project()["project"]["dependencies"]
    }
    assert {"numpy", "scipy", "jax"} <= deps


def test_public_all_fully_resolvable():
    """Every name in ``netrep_tpu.__all__`` must resolve through the lazy
    ``__getattr__`` table — a drifted entry (e.g. a plot export added to
    ``__all__`` but not to the dispatch) would raise AttributeError at the
    exact moment a user (or ``from netrep_tpu import *``) touches it."""
    import netrep_tpu

    for name in netrep_tpu.__all__:
        assert getattr(netrep_tpu, name) is not None, name
    # the reference exports its plot suite at package level (SURVEY.md
    # §2.1: plotModule + per-panel functions) — pin the analogues. They
    # are lazy attributes OUTSIDE __all__ (matplotlib is the optional
    # `plot` extra; star-import on a base install must not touch it)
    pytest.importorskip("matplotlib")
    for name in ("plot_module", "plot_data", "plot_correlation",
                 "plot_network", "plot_contribution", "plot_degree"):
        assert callable(getattr(netrep_tpu, name)), name
        assert name not in netrep_tpu.__all__, name
