"""Autoscaling-fleet tests (ISSUE 19) — CPU-only, in-process, tiny
fixtures: the replica lifecycle transition matrix pinned exactly (an
edge added or removed is a contract change), the autoscaler's
up/down/cooldown decisions deterministic under an injected fake clock,
the scale-to-zero round trip (journal + warm store ARE the fleet state:
a spawn-on-demand replica answers a pre-retirement duplicate from the
adopted journal with ZERO packs and computes fresh keys bit-identical),
and the noticed-eviction handoff: a mid-pack crash followed by
``evict_notice`` migrates every request to the peer with zero lost
work, the partial pack resuming from the SHARED checkpoint directory —
and NO failover events, because a noticed departure is a handoff."""

import json
import os
import threading
import time

import numpy as np
import pytest

from netrep_tpu import module_preservation
from netrep_tpu.data import make_mixed_pair
from netrep_tpu.serve import (
    AutoscaleConfig, Autoscaler, FleetConfig, IllegalTransition,
    ReplicaLifecycle, ServeConfig, build_inprocess_fleet,
    inprocess_spawner,
)
from netrep_tpu.serve.lifecycle import LEGAL_TRANSITIONS, STATES
from netrep_tpu.utils.config import EngineConfig, FaultPolicy

#: the ONE engine config fleet-served runs and their direct twins share
CFG = EngineConfig(chunk_size=16, autotune=False)


@pytest.fixture(scope="module")
def fx():
    mixed = make_mixed_pair(100, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    direct_kw = dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", config=CFG,
    )
    return dict(dn=dn, dc=dc, dd=dd, tn=tn, tc=tc, td=td, assign=assign,
                direct_kw=direct_kw)


def direct(fx, **kw):
    return module_preservation(**fx["direct_kw"], **kw)


def read_events(path):
    return [json.loads(l) for l in open(path, encoding="utf-8")]


def _mk_config(tmp_path):
    """Per-replica ServeConfig factory shared by the static fleet AND
    the autoscaler's spawner — a spawned replica must look exactly like
    a built one (same engine, journal layout, shared checkpoint dir)."""
    def mk(rid, jpath, ckpt):
        return ServeConfig(
            engine=CFG, journal=jpath, checkpoint_dir=ckpt,
            checkpoint_every=16, fleet_label=rid,
            telemetry=str(tmp_path / f"{rid}_tel.jsonl"),
        )
    return mk


def make_fleet(fx, tmp_path, n=2, *, register=True, heartbeat_s=30.0,
               fleet_config_kw=None, start_servers=True):
    """N-replica in-process fleet over the shared fixture pair. The
    heartbeat defaults LONG: these tests drive planned departures and
    fake-clock ticks, and the health loop must never mistake an
    unstarted or mid-drill replica for an unnoticed loss."""
    fc = FleetConfig(telemetry=str(tmp_path / "coord.jsonl"),
                     heartbeat_s=heartbeat_s,
                     **(fleet_config_kw or {}))
    fleet = build_inprocess_fleet(
        n, str(tmp_path / "fleet"), make_config=_mk_config(tmp_path),
        fleet_config=fc, start_servers=start_servers,
    )
    if register:
        fleet.register_dataset("a", "d", network=fx["dn"],
                               correlation=fx["dc"], data=fx["dd"],
                               assignments=fx["assign"])
        fleet.register_dataset("a", "t", network=fx["tn"],
                               correlation=fx["tc"], data=fx["td"])
    return fleet


# ---------------------------------------------------------------------------
# lifecycle state machine
# ---------------------------------------------------------------------------

def test_lifecycle_transition_matrix_pinned(tmp_path):
    """The legal-move table, pinned EXACTLY (the contract lifecycle.py
    points here for): 6 edges, every other ordered pair raises, a
    failed move leaves the state untouched, dead→spawning bumps the
    generation, and every legal transition emits ONE ``replica_state``
    event carrying replica/prev/to/gen/reason."""
    assert STATES == ("spawning", "ready", "draining", "dead")
    assert LEGAL_TRANSITIONS == frozenset({
        ("spawning", "ready"),
        ("spawning", "dead"),
        ("ready", "draining"),
        ("ready", "dead"),
        ("draining", "dead"),
        ("dead", "spawning"),
    })
    # exhaustive sweep: a fresh machine forced into each origin state
    for prev in STATES:
        for to in STATES:
            cycle = ReplicaLifecycle("rX")
            cycle._state = prev           # test-only: set the origin
            if (prev, to) in LEGAL_TRANSITIONS:
                assert cycle.transition(to, reason="pin") == to
                assert cycle.state == to
            else:
                with pytest.raises(IllegalTransition):
                    cycle.transition(to, reason="pin")
                assert cycle.state == prev   # rejected move = no move
    with pytest.raises(IllegalTransition):
        ReplicaLifecycle("rX").transition("zombie")

    # the respawn path bumps the generation and the event stream shows
    # the full walk — one event per transition, nothing else
    from netrep_tpu.utils.telemetry import Telemetry

    tel_path = str(tmp_path / "tel.jsonl")
    tel = Telemetry(tel_path)
    cycle = ReplicaLifecycle("r9", telemetry=tel)
    assert cycle.generation == 0
    cycle.transition("ready", reason="join")
    cycle.transition("dead", reason="lost")
    cycle.transition("spawning", reason="respawn")
    assert cycle.generation == 1
    tel.close()
    ev = [e for e in read_events(tel_path) if e["ev"] == "replica_state"]
    assert [(e["data"]["prev"], e["data"]["to"], e["data"]["gen"],
             e["data"]["reason"]) for e in ev] == [
        ("spawning", "ready", 0, "join"),
        ("ready", "dead", 0, "lost"),
        ("dead", "spawning", 1, "respawn"),
    ]
    assert all(e["data"]["replica"] == "r9" for e in ev)


# ---------------------------------------------------------------------------
# autoscaler decisions under a fake clock
# ---------------------------------------------------------------------------

def test_autoscaler_up_down_cooldown_under_fake_clock(fx, tmp_path):
    """The control loop, tick by tick on an injected clock (workers
    never start, so the backlog is whatever the test queues): backlog
    above the drain threshold scales up to ``max_replicas`` with the
    cooldown between actions; a drained-and-idle fleet retires one
    replica per cooldown window, newest id first, all the way to ZERO —
    leaving ``last_journal`` as the state a future spawn adopts."""
    fleet = make_fleet(fx, tmp_path, n=1, start_servers=False,
                      fleet_config_kw=dict(rate_pps=10.0))
    clk = {"t": 0.0}
    spawn = inprocess_spawner(str(tmp_path / "fleet"),
                              make_config=_mk_config(tmp_path),
                              start_servers=False)
    scaler = Autoscaler(
        fleet, spawn,
        AutoscaleConfig(scale_up_drain_s=10.0, scale_down_idle_s=10.0,
                        min_replicas=0, max_replicas=3, cooldown_s=2.0),
        clock=lambda: clk["t"], start=False,
    )
    assert fleet.autoscaler is scaler
    try:
        home = fleet.route("a", "d", "t")
        assert home.rid == "r0"
        for i in range(3):
            home.server.submit("a", "d", "t", n_perm=256, seed=i)
        # 768 queued perms / 10 pps = 76.8s drain, far above the 10s
        # enter threshold: scale up
        assert scaler.tick(now=0.0) == "up"
        assert sorted(fleet.live_replicas()) == ["r0", "r1"]
        # still 38.4s with two replicas, but the cooldown holds
        assert scaler.tick(now=1.0) is None
        clk["t"] = 3.0
        assert scaler.tick(now=3.0) == "up"
        assert sorted(fleet.live_replicas()) == ["r0", "r1", "r2"]
        # at max_replicas: the signal still says up, the bound wins
        clk["t"] = 6.0
        assert scaler.tick(now=6.0) is None
        # the backlog drains (cleared in place — workers never ran)
        with home.server._work:
            for t in home.server._tenants.values():
                t.pending.clear()
        clk["t"] = 7.0
        assert scaler.tick(now=7.0) is None     # idle periods just began
        # every replica has now been idle >= 10s: retire ONE per
        # cooldown window, newest id first
        clk["t"] = 17.0
        assert scaler.tick(now=17.0) == "down"
        assert sorted(fleet.live_replicas()) == ["r0", "r1"]
        clk["t"] = 18.0
        assert scaler.tick(now=18.0) is None    # cooldown again
        clk["t"] = 20.0
        assert scaler.tick(now=20.0) == "down"
        assert sorted(fleet.live_replicas()) == ["r0"]
        clk["t"] = 23.0
        assert scaler.tick(now=23.0) == "down"  # min_replicas=0: to zero
        assert fleet.live_replicas() == {}
        # scale-to-zero left the persistent state behind
        assert fleet.last_journal is not None
        assert os.path.exists(fleet.last_journal)
        st = fleet.stats()
    finally:
        fleet.close(drain=False)
    assert all(row["alive"] is False and row["state"] == "dead"
               for row in st["replicas"].values())
    ev = read_events(str(tmp_path / "coord.jsonl"))
    ups = [e["data"] for e in ev if e["ev"] == "autoscale_up"]
    downs = [e["data"] for e in ev if e["ev"] == "autoscale_down"]
    assert [u["replica"] for u in ups] == ["r1", "r2"]
    assert all(u["reason"] == "backlog" and u["est_drain_s"] > 10.0
               for u in ups)
    assert [d["replica"] for d in downs] == ["r2", "r1", "r0"]
    assert [d["replicas"] for d in downs] == [2, 1, 0]
    assert all(d["idle_s"] >= 10.0 for d in downs)
    zero = [e["data"] for e in ev if e["ev"] == "scale_to_zero"]
    assert len(zero) == 1 and zero[0]["replica"] == "r0"
    assert zero[0]["journal"] == fleet.last_journal
    # the retired replicas walked the machine: ready→draining(retire)→dead
    r2_states = [(e["data"]["prev"], e["data"]["to"], e["data"]["reason"])
                 for e in ev if e["ev"] == "replica_state"
                 and e["data"]["replica"] == "r2"]
    assert r2_states == [
        ("spawning", "ready", "join"),
        ("ready", "draining", "retire"),
        ("draining", "dead", "drained"),
    ]


# ---------------------------------------------------------------------------
# scale to zero and back: journal + warm store ARE the fleet state
# ---------------------------------------------------------------------------

def test_scale_to_zero_round_trip_bit_identical(fx, tmp_path):
    """Retire the last replica (scale-to-zero), then submit against the
    EMPTY fleet: the attached autoscaler spawns on demand, the newcomer
    adopts the last drained replica's full journal copy, a duplicate of
    a pre-retirement request answers from the adopted journal with ZERO
    packs dispatched, and a fresh key computes bit-identical to a
    direct call — nothing about the fleet's death and rebirth is
    observable in the numbers."""
    fleet = make_fleet(fx, tmp_path, n=1)
    spawn = inprocess_spawner(str(tmp_path / "fleet"),
                              make_config=_mk_config(tmp_path))
    Autoscaler(fleet, spawn,
               AutoscaleConfig(min_replicas=0, max_replicas=2,
                               cooldown_s=0.0),
               start=False)
    try:
        r1 = fleet.analyze("a", "d", "t", n_perm=32, seed=3,
                           idempotency_key="K", timeout=600)
        out = fleet.retire_replica("r0")
        assert out is not None and out["replica"] == "r0"
        assert fleet.live_replicas() == {}
        assert fleet.last_journal and os.path.exists(fleet.last_journal)
        # the empty-fleet submit queues behind a spawn-on-demand boot
        r2 = fleet.analyze("a", "d", "t", n_perm=32, seed=3,
                           idempotency_key="K", timeout=600)
        st = fleet.stats()
        fresh = fleet.analyze("a", "d", "t", n_perm=32, seed=5,
                              timeout=600)
    finally:
        fleet.close()
    np.testing.assert_array_equal(np.asarray(r1["p_values"]),
                                  np.asarray(r2["p_values"]))
    np.testing.assert_array_equal(np.asarray(r1["counts_hi"]),
                                  np.asarray(r2["counts_hi"]))
    # the duplicate was a pure journal answer on the newcomer
    assert sorted(st["replicas"]) == ["r0", "r1"]
    assert st["replicas"]["r0"]["state"] == "dead"
    assert st["replicas"]["r1"]["alive"] is True
    assert st["replicas"]["r1"]["packs"] == 0
    d = direct(fx, n_perm=32, seed=5)
    np.testing.assert_array_equal(fresh["observed"], d.observed)
    np.testing.assert_array_equal(fresh["p_values"],
                                  np.asarray(d.p_values))
    ev = read_events(str(tmp_path / "coord.jsonl"))
    names = [e["ev"] for e in ev]
    assert "scale_to_zero" in names
    sod = [e["data"] for e in ev if e["ev"] == "spawn_on_demand"]
    assert sod and sod[0]["replica"] == "r1"
    assert sod[0]["reason"] == "empty_fleet"
    # a planned departure is NOT a failover
    assert "replica_lost" not in names
    assert "failover_start" not in names
    r0_states = [(e["data"]["to"], e["data"]["reason"]) for e in ev
                 if e["ev"] == "replica_state"
                 and e["data"]["replica"] == "r0"]
    assert ("draining", "retire") in r0_states


# ---------------------------------------------------------------------------
# noticed eviction: handoff, not failover
# ---------------------------------------------------------------------------

def test_evict_notice_mid_pack_handoff_zero_recompute(fx, tmp_path):
    """The tentpole acceptance for preemption: a replica crashes
    mid-pack (checkpoint at 16, SimulatedCrash at 24 — the in-process
    SIGKILL stand-in), the platform's eviction notice lands, and the
    handoff — ring removal, bounded drain, journal-tail pre-ship, peer
    adoption — migrates all three requests: counts/p-values/adaptive
    decisions bit-identical to direct calls, the partial pack RESUMED
    from the shared checkpoint directory, and the coordinator's event
    story is evict_notice → rebalance → evict_handoff_done with NO
    failover events at all (the health loop never fires — the notice
    preempted it)."""
    fleet = make_fleet(fx, tmp_path, n=2)
    submits = [
        ("k1", dict(n_perm=64, seed=3)),
        ("k2", dict(n_perm=64, seed=5)),
        ("k3", dict(n_perm=32, seed=11, adaptive=True)),
    ]
    try:
        home = fleet.route("a", "d", "t")
        peer_rid = [r for r in ("r0", "r1") if r != home.rid][0]
        home.arm_fault_plan(FaultPolicy(plan="crash@24",
                                        backoff_base_s=0.0,
                                        backoff_jitter=0.0))
        results = {}
        errors = []

        def worker(k, kw):
            try:
                results[k] = fleet.analyze("a", "d", "t",
                                           idempotency_key=k,
                                           timeout=600, **kw)
            except Exception as e:   # surfaced after join
                errors.append(f"{k}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=worker, args=s, daemon=True)
                   for s in submits]
        for t in threads:
            t.start()
        # wait for the crash to land mid-pack (the worker thread dies
        # at permutation 24, after the 16-perm checkpoint)
        deadline = time.monotonic() + 120
        while home.alive() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not home.alive(), "SimulatedCrash never fired"
        # the eviction notice for the doomed capacity
        out = fleet.evict_notice(home.rid, grace_s=1.0)
        for t in threads:
            t.join(timeout=600)
        assert not errors, errors
        st = fleet.stats()
    finally:
        fleet.close()
    assert out is not None
    assert out["replica"] == home.rid and out["peer"] == peer_rid
    assert out["s"] > 0 and out["requeued"] == 3
    dead_row = st["replicas"][home.rid]
    assert dead_row["alive"] is False and dead_row["state"] == "dead"
    assert st["replicas"][peer_rid]["done"] == 3
    for k, kw in submits:
        d = direct(fx, **kw)
        np.testing.assert_array_equal(results[k]["observed"], d.observed)
        np.testing.assert_array_equal(results[k]["p_values"],
                                      np.asarray(d.p_values))
        if kw.get("adaptive"):
            np.testing.assert_array_equal(results[k]["n_perm_used"],
                                          np.asarray(d.n_perm_used))
    ev = read_events(str(tmp_path / "coord.jsonl"))
    names = [e["ev"] for e in ev]
    # handoff, not failover: the noticed departure never shows up as a
    # loss
    assert "replica_lost" not in names
    assert "failover_start" not in names
    assert "failover_done" not in names
    notice = [e["data"] for e in ev if e["ev"] == "evict_notice"]
    assert notice and notice[0]["replica"] == home.rid
    assert notice[0]["grace_s"] == pytest.approx(1.0)
    reb = [e["data"] for e in ev if e["ev"] == "ring_rebalanced"
           and e["data"].get("reason") == "evict"]
    assert reb and home.rid not in reb[0]["members"]
    done = [e["data"] for e in ev if e["ev"] == "evict_handoff_done"]
    assert done and done[0]["peer"] == peer_rid
    assert done[0]["requeued"] == 3 and done[0]["s"] > 0
    home_states = [(e["data"]["to"], e["data"]["reason"]) for e in ev
                   if e["ev"] == "replica_state"
                   and e["data"]["replica"] == home.rid]
    assert ("draining", "evict") in home_states
    assert home_states[-1] == ("dead", "drained")
    # the peer ADOPTED (journal_replayed) and RESUMED the partial pack
    # from the shared checkpoint dir — zero recompute of perms 1..16
    pe = read_events(str(tmp_path / f"{peer_rid}_tel.jsonl"))
    replay = [e for e in pe if e["ev"] == "journal_replayed"]
    assert replay and replay[0]["data"]["adopted"] is True
    assert replay[0]["data"]["requeued"] == 3
    resumed = [e for e in pe if e["ev"] == "checkpoint_resumed"]
    assert resumed and resumed[0]["data"]["completed"] >= 16
    # the ops surfaces tell the eviction story
    from netrep_tpu.utils.telemetry import render_recovery, \
        render_replicas

    timeline = render_recovery(str(tmp_path / "coord.jsonl"))
    assert "evict_notice" in timeline
    assert "evict_handoff_done" in timeline
    assert "failover" not in timeline
    section = render_replicas(str(tmp_path / "coord.jsonl"))
    assert home.rid in section and "evict" in section
