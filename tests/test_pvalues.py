"""Tests for the Phipson–Smyth permp reimplementation and permutation-count
planning (SURVEY.md §7 'Exact p-values' hard requirement)."""

import numpy as np
import pytest

from netrep_tpu.ops import pvalues as pv


def test_permp_infinite_space_is_biased_estimator():
    p = pv.permp(np.array([0, 5, 100]), nperm=100, total_nperm=None)
    np.testing.assert_allclose(p, [1 / 101, 6 / 101, 1.0])


def test_permp_exact_small_space():
    """Exact method: mean of Binomial CDFs over attainable true p-values."""
    x, nperm, mt = 3, 50, 20
    from scipy.stats import binom

    expected = np.mean([binom.cdf(x, nperm, v / mt) for v in range(1, mt + 1)])
    got = pv.permp(np.array([x]), nperm, total_nperm=mt, method="exact")[0]
    assert abs(got - expected) < 1e-12


def test_permp_approx_converges_to_exact():
    """The integral approximation tracks the exact sum for moderate spaces."""
    x, nperm, mt = 2, 200, 5000
    ex = pv.permp(np.array([x]), nperm, total_nperm=mt, method="exact")[0]
    ap = pv.permp(np.array([x]), nperm, total_nperm=mt, method="approximate")[0]
    assert abs(ex - ap) < 1e-4


def test_permp_never_zero():
    p = pv.permp(np.array([0]), nperm=1000, total_nperm=1e300)
    assert p[0] > 0


def test_permp_auto_switch():
    small = pv.permp(np.array([1]), 100, total_nperm=100, method="auto")
    exact = pv.permp(np.array([1]), 100, total_nperm=100, method="exact")
    np.testing.assert_allclose(small, exact)


def test_exceedance_counts_alternatives():
    obs = np.array([2.0])
    nulls = np.array([[1.0], [2.0], [3.0], [np.nan]])
    c, n = pv.exceedance_counts(obs, nulls, "greater")
    assert c[0] == 2 and n[0] == 3
    c, _ = pv.exceedance_counts(obs, nulls, "less")
    assert c[0] == 2
    c, _ = pv.exceedance_counts(obs, nulls, "two.sided")
    assert c[0] == 2
    with pytest.raises(ValueError):
        pv.exceedance_counts(obs, nulls, "bogus")


def test_permutation_pvalues_shapes_and_nan():
    rng = np.random.default_rng(0)
    obs = np.array([[3.0, np.nan], [0.0, 1.0]])
    nulls = rng.standard_normal((500, 2, 2))
    p = pv.permutation_pvalues(obs, nulls, "greater")
    assert p.shape == (2, 2)
    assert np.isnan(p[0, 1])
    assert p[0, 0] < 0.05          # obs=3 is far in the right tail
    assert 0.0 < p[1, 0] <= 1.0


def test_two_sided_doubles_and_caps():
    obs = np.array([0.0])
    nulls = np.random.default_rng(1).standard_normal((999, 1))
    p = pv.permutation_pvalues(obs, nulls, "two.sided")
    assert 0.9 <= p[0] <= 1.0  # dead-centre observed → p ≈ 1


def test_total_permutations():
    # pool of 5, one module of 2: 5*4 = 20 ordered assignments
    assert abs(pv.total_permutations(5, [2]) - 20) < 1e-9
    assert pv.total_permutations(10, [11]) == float("inf")
    assert np.isinf(pv.total_permutations(20000, [100] * 50))


def test_required_perms():
    assert pv.required_perms(0.05) == 19
    assert pv.required_perms(0.05, n_tests=10) == 199
    assert pv.required_perms(0.05, alternative="two.sided") == 39
    with pytest.raises(ValueError):
        pv.required_perms(0.0)
