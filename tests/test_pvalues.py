"""Tests for the Phipson–Smyth permp reimplementation and permutation-count
planning (SURVEY.md §7 'Exact p-values' hard requirement)."""

import numpy as np
import pytest

from netrep_tpu.ops import pvalues as pv


def test_permp_infinite_space_is_biased_estimator():
    p = pv.permp(np.array([0, 5, 100]), nperm=100, total_nperm=None)
    np.testing.assert_allclose(p, [1 / 101, 6 / 101, 1.0])


def test_permp_exact_small_space():
    """Exact method: mean of Binomial CDFs over attainable true p-values."""
    x, nperm, mt = 3, 50, 20
    from scipy.stats import binom

    expected = np.mean([binom.cdf(x, nperm, v / mt) for v in range(1, mt + 1)])
    got = pv.permp(np.array([x]), nperm, total_nperm=mt, method="exact")[0]
    assert abs(got - expected) < 1e-12


def test_permp_approx_converges_to_exact():
    """The integral approximation tracks the exact sum for moderate spaces."""
    x, nperm, mt = 2, 200, 5000
    ex = pv.permp(np.array([x]), nperm, total_nperm=mt, method="exact")[0]
    ap = pv.permp(np.array([x]), nperm, total_nperm=mt, method="approximate")[0]
    assert abs(ex - ap) < 1e-4


def test_permp_never_zero():
    p = pv.permp(np.array([0]), nperm=1000, total_nperm=1e300)
    assert p[0] > 0


def test_permp_auto_switch():
    small = pv.permp(np.array([1]), 100, total_nperm=100, method="auto")
    exact = pv.permp(np.array([1]), 100, total_nperm=100, method="exact")
    np.testing.assert_allclose(small, exact)


def test_exceedance_counts_alternatives():
    obs = np.array([2.0])
    nulls = np.array([[1.0], [2.0], [3.0], [np.nan]])
    c, n = pv.exceedance_counts(obs, nulls, "greater")
    assert c[0] == 2 and n[0] == 3
    c, _ = pv.exceedance_counts(obs, nulls, "less")
    assert c[0] == 2
    c, _ = pv.exceedance_counts(obs, nulls, "two.sided")
    assert c[0] == 2
    with pytest.raises(ValueError):
        pv.exceedance_counts(obs, nulls, "bogus")


def test_permutation_pvalues_shapes_and_nan():
    rng = np.random.default_rng(0)
    obs = np.array([[3.0, np.nan], [0.0, 1.0]])
    nulls = rng.standard_normal((500, 2, 2))
    p = pv.permutation_pvalues(obs, nulls, "greater")
    assert p.shape == (2, 2)
    assert np.isnan(p[0, 1])
    assert p[0, 0] < 0.05          # obs=3 is far in the right tail
    assert 0.0 < p[1, 0] <= 1.0


def test_two_sided_doubles_and_caps():
    obs = np.array([0.0])
    nulls = np.random.default_rng(1).standard_normal((999, 1))
    p = pv.permutation_pvalues(obs, nulls, "two.sided")
    assert 0.9 <= p[0] <= 1.0  # dead-centre observed → p ≈ 1


def test_total_permutations():
    # pool of 5, one module of 2: 5*4 = 20 ordered assignments
    assert abs(pv.total_permutations(5, [2]) - 20) < 1e-9
    assert pv.total_permutations(10, [11]) == float("inf")
    assert np.isinf(pv.total_permutations(20000, [100] * 50))


def test_required_perms():
    assert pv.required_perms(0.05) == 19
    assert pv.required_perms(0.05, n_tests=10) == 199
    assert pv.required_perms(0.05, alternative="two.sided") == 39
    with pytest.raises(ValueError):
        pv.required_perms(0.0)


# ---------------------------------------------------------------------------
# statmod fidelity (VERDICT r1 item 10): statmod itself cannot run here (no
# R, empty reference mount), but its exact method IS the Phipson–Smyth
# estimator mean_v P(B <= x | p=v/mt) — pinned below against an independent
# oracle in exact rational arithmetic (provably correct by enumeration).
# ---------------------------------------------------------------------------

def _permp_exact_fraction(x: int, nperm: int, mt: int):
    """Ground-truth Phipson–Smyth exact estimator via fractions.Fraction:
    mean over v=1..mt of sum_{j<=x} C(nperm,j) (v/mt)^j (1-v/mt)^(nperm-j)."""
    from fractions import Fraction
    from math import comb

    acc = Fraction(0)
    for v in range(1, mt + 1):
        p = Fraction(v, mt)
        cdf = sum(
            comb(nperm, j) * p**j * (1 - p) ** (nperm - j)
            for j in range(0, min(x, nperm) + 1)
        )
        acc += cdf
    return acc / mt


@pytest.mark.parametrize(
    "x,nperm,mt",
    [(0, 1, 2), (1, 2, 2), (0, 5, 6), (3, 10, 12), (7, 20, 24), (0, 30, 5)],
)
def test_permp_exact_matches_rational_oracle(x, nperm, mt):
    got = pv.permp(np.array([x]), nperm, total_nperm=mt, method="exact")[0]
    want = float(_permp_exact_fraction(x, nperm, mt))
    assert abs(got - want) < 1e-12, (got, want)


def test_permp_exact_hand_computed_cases():
    # mt=2, nperm=1, x=0: mean(P(B<=0|.5), P(B<=0|1)) = (1/2 + 0)/2 = 1/4
    assert abs(pv.permp([0], 1, 2, method="exact")[0] - 0.25) < 1e-15
    # mt=2, nperm=2, x=1: mean(pbinom(1,2,.5), pbinom(1,2,1)) = (3/4 + 0)/2
    assert abs(pv.permp([1], 2, 2, method="exact")[0] - 0.375) < 1e-15
    # x=nperm: every CDF term is 1 → p = 1 exactly
    assert pv.permp([10], 10, 50, method="exact")[0] == pytest.approx(1.0)


def test_permp_approximate_integral_correction():
    """The approximate method is (x+1)/(nperm+1) minus the boundary integral
    ∫_0^{1/(2mt)} pbinom(x, nperm, u) du; for x=0 that integral has the
    closed form [1 - (1-u)^(n+1)]/(n+1) evaluated at u=1/(2mt)."""
    nperm, mt = 99, 1_000_000
    got = pv.permp([0], nperm, mt, method="approximate")[0]
    u = 0.5 / mt
    corr = (1.0 - (1.0 - u) ** (nperm + 1)) / (nperm + 1)
    want = 1.0 / (nperm + 1) - corr
    assert abs(got - want) < 1e-14


def test_permp_auto_threshold_mirrors_statmod_rule():
    # auto = exact at mt <= 10_000, approximate above (statmod's documented
    # switch; see permp docstring "Fidelity" note)
    x, nperm = np.array([3]), 50
    at = pv.permp(x, nperm, 10_000, method="auto")
    ex = pv.permp(x, nperm, 10_000, method="exact")
    assert at[0] == ex[0]
    above = pv.permp(x, nperm, 10_001, method="auto")
    ap = pv.permp(x, nperm, 10_001, method="approximate")
    assert above[0] == ap[0]


# --- gpd_tail_pvalues (ISSUE 16: generalized-Pareto tail sharpening) -------

def test_gpd_tail_resolves_far_tail_below_1e8():
    """A p < 1e-8 cell resolved from 10^4 permutations: the exact estimator
    bottoms out at 1/(nperm+1) ≈ 1e-4, while the gated GPD fit over the
    250-exceedance tail extrapolates the true far-tail probability. The
    null is drawn from an actual GPD (shape 0.1) so the extrapolated value
    can be checked against the known survival function."""
    import scipy.stats as st

    rng = np.random.default_rng(7)
    nulls = st.genpareto.rvs(0.1, size=(10_000, 1), random_state=rng)
    obs = np.array([60.0])
    p_tail, ok = pv.gpd_tail_pvalues(obs, nulls)
    assert ok[0]
    assert 0.0 < p_tail[0] < 1e-8
    # within two orders of magnitude of the true tail probability — an
    # 11-decade extrapolation from 10^4 draws cannot be tighter
    true = float(st.genpareto.sf(60.0, 0.1))
    assert 1e-2 < p_tail[0] / true < 1e2
    # the exact estimator cannot go below 1/(nperm+1)
    exact = pv.permutation_pvalues(obs, nulls)
    assert exact[0] >= 1.0 / 10_001


def test_gpd_tail_exponential_matches_known_tail():
    """Exponential nulls are exactly GPD(ξ=0): the fit must pass the A–D
    gate at the first (250-exceedance) threshold and land near exp(-obs)."""
    rng = np.random.default_rng(0)
    nulls = rng.exponential(size=(10_000, 1))
    p_tail, ok = pv.gpd_tail_pvalues(np.array([18.0]), nulls)
    assert ok[0]
    assert p_tail[0] < 1e-6  # true sf ≈ 1.5e-8; fitted endpoint may clip


def test_gpd_tail_ad_gate_refuses_ill_behaved_tail():
    """Heavy-tailed fixture whose extreme tail collapses onto three
    discrete atoms: no GPD fits that, and the Anderson–Darling gate must
    refuse at every candidate exceedance count (tail_ok False, p NaN)."""
    rng = np.random.default_rng(1)
    base = np.abs(rng.standard_cauchy(10_000))
    m = float(base.max())
    atoms = m * np.array([2.0, 2.5, 3.0])  # strictly above every draw
    idx = np.argsort(base)
    base[idx[-400:]] = atoms[rng.integers(0, 3, 400)]
    p_tail, ok = pv.gpd_tail_pvalues(np.array([10.0 * m]), base[:, None])
    assert not ok[0]
    assert np.isnan(p_tail[0])


def test_gpd_tail_dense_cells_and_nan_left_to_exact_estimator():
    rng = np.random.default_rng(2)
    nulls = rng.normal(size=(10_000, 2))
    # observed at the median: >= 10 exceedances → exact p is in charge
    p_tail, ok = pv.gpd_tail_pvalues(np.array([0.0, np.nan]), nulls)
    assert not ok.any()
    assert np.isnan(p_tail).all()


def test_gpd_tail_less_and_two_sided_mirror_greater():
    rng = np.random.default_rng(0)
    nulls = rng.exponential(size=(10_000, 1))
    p_hi, ok_hi = pv.gpd_tail_pvalues(np.array([18.0]), nulls)
    p_lo, ok_lo = pv.gpd_tail_pvalues(
        np.array([-18.0]), -nulls, alternative="less"
    )
    assert ok_lo[0] == ok_hi[0]
    assert p_lo[0] == pytest.approx(p_hi[0])
    p_2s, ok_2s = pv.gpd_tail_pvalues(
        np.array([18.0]), nulls, alternative="two.sided"
    )
    assert ok_2s[0]
    assert p_2s[0] == pytest.approx(min(2.0 * p_hi[0], 1.0))
    with pytest.raises(ValueError):
        pv.gpd_tail_pvalues(np.array([1.0]), nulls, alternative="bogus")


def test_gpd_tail_refuses_bf16_screened_nulls():
    """ISSUE 17 satellite (the ISSUE 16 caveat): the screened fast-pass
    stores decided permutations' bf16-rounded VALUES — exceedance counts
    stay exact, but the GPD threshold-excess fit reads the extreme draws
    themselves, and the quantized tail plateaus make it meaningless. The
    fit must refuse loudly, not produce a confident wrong extrapolation."""
    rng = np.random.default_rng(0)
    nulls = rng.exponential(size=(10_000, 1))
    with pytest.raises(ValueError, match="bf16-screened"):
        pv.gpd_tail_pvalues(np.array([18.0]), nulls, nulls_exact=False)
    # the exact counts path is explicitly unaffected by screening: the
    # same array fits fine when flagged exact (the default)
    p, ok = pv.gpd_tail_pvalues(np.array([18.0]), nulls, nulls_exact=True)
    assert ok[0] and np.isfinite(p[0])


def test_result_nulls_exact_gates_tail_and_roundtrips(tmp_path):
    """A result flagged ``nulls_exact=False`` refuses ``tail_pvalues()``
    with the f32-rerun guidance, and the flag survives save/load (an
    additive meta key: old files default to exact)."""
    from netrep_tpu.models.results import PreservationResult

    rng = np.random.default_rng(1)
    k = 1
    nulls = rng.exponential(size=(2_000, k, 7))
    obs = np.full((k, 7), 30.0)
    kw = dict(
        discovery="a", test="b", module_labels=["1"], observed=obs,
        p_values=np.full((k, 7), 1e-3), n_vars_present=np.array([5]),
        prop_vars_present=np.array([1.0]), total_size=np.array([5]),
        alternative="greater", n_perm=2_000, completed=2_000,
    )
    screened = PreservationResult(nulls=nulls, nulls_exact=False, **kw)
    with pytest.raises(ValueError, match="null_precision='f32'"):
        screened.tail_pvalues()
    screened.save(str(tmp_path / "r.npz"))
    back = PreservationResult.load(str(tmp_path / "r.npz"))
    assert back.nulls_exact is False
    with pytest.raises(ValueError, match="bf16"):
        back.tail_pvalues()
    # exact result: fits, persists, and reloads as exact
    exact = PreservationResult(nulls=nulls, **kw)
    p_tail, ok = exact.tail_pvalues()
    assert p_tail.shape == (k, 7)
    exact.save(str(tmp_path / "e.npz"))
    assert PreservationResult.load(str(tmp_path / "e.npz")).nulls_exact is True


def test_combine_drops_tail_refit_when_any_block_screened():
    """Pooling an exact block with a screened block quantizes part of the
    pooled tail: combine_analyses must not refit the GPD over it — the
    combined result carries ``nulls_exact=False`` and no ``p_tail``."""
    from netrep_tpu.models.results import PreservationResult, combine_analyses

    rng = np.random.default_rng(2)
    k = 1

    def block(seed, exact):
        r = np.random.default_rng(seed)
        return PreservationResult(
            discovery="a", test="b", module_labels=["1"],
            observed=np.full((k, 7), 30.0),
            nulls=r.exponential(size=(2_000, k, 7)),
            nulls_exact=exact,
            p_values=np.full((k, 7), 1e-3), n_vars_present=np.array([5]),
            prop_vars_present=np.array([1.0]), total_size=np.array([5]),
            alternative="greater", n_perm=2_000, completed=2_000,
        )

    a, b = block(10, True), block(11, False)
    a.tail_pvalues()  # the exact block had a tail fit before pooling
    merged = combine_analyses(a, b)
    assert merged.nulls_exact is False
    assert merged.p_tail is None
    assert merged.completed == 4_000
    with pytest.raises(ValueError, match="bf16"):
        merged.tail_pvalues()
