"""Crash-safe serving tests (ISSUE 10) — CPU-only, in-process, tiny
fixtures: journal round-trip (torn final line tolerated), idempotency-key
dedup (in-flight and completed), the kill→recover bit-parity drill
(SimulatedCrash mid-pack → fresh server with ``recover=True`` → results
bit-identical to direct calls, partial packs resumed from checkpoint),
deadline expiry mid-pack with survivor parity, brownout enter/exit
ordering with ``retry_after_s``, bounded-drain journaling, wire-line
hardening, and deterministic client retry backoff."""

import json
import threading
import time

import numpy as np
import pytest

from netrep_tpu import module_preservation
from netrep_tpu.data import make_mixed_pair
from netrep_tpu.serve import (
    InProcessClient, PreservationServer, QueueFull, ServeConfig, ServeError,
    retry_delay,
)
from netrep_tpu.serve import journal as jnl
from netrep_tpu.utils.config import EngineConfig, FaultPolicy
from netrep_tpu.utils.faults import parse_plan

#: the ONE engine config served runs and their direct-call twins share
CFG = EngineConfig(chunk_size=16, autotune=False)


@pytest.fixture(scope="module")
def fx():
    mixed = make_mixed_pair(100, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    direct_kw = dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t", config=CFG,
    )
    return dict(dn=dn, dc=dc, dd=dd, tn=tn, tc=tc, td=td, assign=assign,
                direct_kw=direct_kw)


def make_server(fx, tmp_path, *, tenants=("a",), start=True, tel="tel",
                **cfg_kw):
    cfg_kw.setdefault("engine", CFG)
    cfg_kw.setdefault("telemetry", str(tmp_path / f"{tel}.jsonl"))
    srv = PreservationServer(ServeConfig(**cfg_kw), start=start)
    client = InProcessClient(srv)
    for t in tenants:
        client.register_dataset(t, "d", network=fx["dn"],
                                correlation=fx["dc"], data=fx["dd"],
                                assignments=fx["assign"])
        client.register_dataset(t, "t", network=fx["tn"],
                                correlation=fx["tc"], data=fx["td"])
    return srv, client


def read_events(path):
    return [json.loads(l) for l in open(path, encoding="utf-8")]


def direct(fx, **kw):
    return module_preservation(**fx["direct_kw"], **kw)


# ---------------------------------------------------------------------------
# journal round-trip
# ---------------------------------------------------------------------------

def test_journal_round_trip_and_torn_final_line(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = jnl.RequestJournal(path)
    j.append("tenant", tenant="a", weight=2)
    j.append("accepted", seq=1, id="r1", key="k1", tenant="a",
             discovery="d", test="t",
             params={"n_perm": 64, "seed": 3})
    j.append("accepted", seq=2, id="r2", key="k2", tenant="a",
             discovery="d", test="t",
             params={"n_perm": 32, "seed": 5})
    j.append("done", seq=1, id="r1", key="k1", tenant="a",
             digest="abc", result={"p_values": [0.1, 0.2]})
    j.close()
    # a crash mid-append leaves a torn final line: tolerated like the
    # telemetry sink's
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"jv": 1, "kind": "done", "seq": 2, "key": "k2", "trunc')
    state = jnl.scan(path)
    assert state["tenants"] == {"a": 2}
    assert list(state["results"]) == ["k1"]
    assert [r["key"] for r in state["pending"]] == ["k2"]
    assert state["n_accepted"] == 2


def test_journal_accepted_is_durable_before_submit_returns(fx, tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    srv, client = make_server(fx, tmp_path, start=False, journal=jpath)
    client.submit("a", "d", "t", n_perm=32, seed=1, idempotency_key="k1")
    # the fsynced accepted record is on disk BEFORE the worker ever runs
    state = jnl.scan(jpath)
    assert [r["key"] for r in state["pending"]] == ["k1"]
    rec = state["pending"][0]
    assert rec["tenant"] == "a" and rec["params"]["n_perm"] == 32
    assert rec["params"]["seed"] == 1 and len(rec["digests"]) == 2
    srv.close(drain=False)


# ---------------------------------------------------------------------------
# idempotency dedup (the acceptance-pinned contract)
# ---------------------------------------------------------------------------

def test_idempotency_dedup_after_completion_never_recomputes(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, journal=str(tmp_path / "j"))
    try:
        r1 = client.analyze("a", "d", "t", n_perm=32, seed=3,
                            idempotency_key="K", timeout=600)
        packs_after_first = srv.stats()["packs"]
        r2 = client.analyze("a", "d", "t", n_perm=32, seed=3,
                            idempotency_key="K", timeout=600)
        st = srv.stats()
    finally:
        srv.close()
    # the duplicate was answered from the stored result: same object-level
    # numbers, NO new pack dispatched, dedup counted + event emitted
    np.testing.assert_array_equal(r1["p_values"], r2["p_values"])
    assert r2["request_id"] == r1["request_id"]
    assert st["packs"] == packs_after_first
    assert st["tenants"]["a"]["deduped"] == 1
    ev = read_events(str(tmp_path / "tel.jsonl"))
    dedup = [e for e in ev if e["ev"] == "request_deduped"]
    assert dedup and dedup[0]["data"]["state"] == "completed"
    assert dedup[0]["data"]["key"] == "K"


def test_idempotency_dedup_attaches_to_inflight_request(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, start=False)
    h1 = client.submit("a", "d", "t", n_perm=32, seed=3,
                       idempotency_key="K")
    h2 = client.submit("a", "d", "t", n_perm=32, seed=3,
                       idempotency_key="K")
    assert h2 is h1                      # one queued computation
    srv.start()
    try:
        res = client.result(h1, timeout=600)
    finally:
        srv.close()
    assert res["completed"] == 32
    ev = read_events(str(tmp_path / "tel.jsonl"))
    dedup = [e for e in ev if e["ev"] == "request_deduped"]
    assert dedup and dedup[0]["data"]["state"] == "inflight"


# ---------------------------------------------------------------------------
# kill -> recover bit parity (the tentpole acceptance)
# ---------------------------------------------------------------------------

def _crash_server(fx, tmp_path, jpath, plan, submits, tel="tel_crash"):
    """Boot a journaled server with an injected crash, submit, and wait
    for the worker thread to die (the in-process SIGKILL)."""
    srv, client = make_server(
        fx, tmp_path, start=False, journal=jpath, checkpoint_every=16,
        tel=tel,
        fault_policy=FaultPolicy(plan=plan, backoff_base_s=0.0,
                                 backoff_jitter=0.0),
    )
    handles = [client.submit("a", "d", "t", idempotency_key=k, **kw)
               for k, kw in submits]
    srv.start()
    deadline = time.monotonic() + 300
    while srv._worker.is_alive() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not srv._worker.is_alive(), "injected crash never fired"
    return srv, handles


def test_kill_recover_completes_bit_identically(fx, tmp_path):
    """SIGKILL stand-in mid-pack → restart with recover=True → every
    journaled request completes with counts/p-values bit-identical to an
    uninterrupted server (= the direct call, by the PR 7 parity pin) —
    resuming the partial pack from its checkpoint, not from scratch."""
    jpath = str(tmp_path / "j.jsonl")
    submits = [
        ("k1", dict(n_perm=64, seed=3)),
        ("k2", dict(n_perm=64, seed=5)),
        ("k3", dict(n_perm=32, seed=11, adaptive=True)),
    ]
    srv1, handles = _crash_server(fx, tmp_path, jpath, "crash@24", submits)
    assert not any(h.done.is_set() for h in handles)  # all died with it

    srv2 = PreservationServer(ServeConfig(
        engine=CFG, journal=jpath, recover=True, checkpoint_every=16,
        telemetry=str(tmp_path / "tel_rec.jsonl"),
    ))
    client2 = InProcessClient(srv2)
    try:
        results = {
            k: client2.analyze("a", "d", "t", idempotency_key=k,
                               timeout=600, **kw)
            for k, kw in submits
        }
    finally:
        srv2.close()
    for k, kw in submits:
        d = direct(fx, **kw)
        np.testing.assert_array_equal(results[k]["observed"], d.observed)
        np.testing.assert_array_equal(results[k]["p_values"],
                                      np.asarray(d.p_values))
        if kw.get("adaptive"):
            np.testing.assert_array_equal(results[k]["n_perm_used"],
                                          np.asarray(d.n_perm_used))
    ev = read_events(str(tmp_path / "tel_rec.jsonl"))
    replay = [e for e in ev if e["ev"] == "journal_replayed"]
    assert replay and replay[0]["data"]["requeued"] == 3
    # the partial pack resumed from its checkpoint: the crash landed past
    # the first checkpoint_every boundary, so recovery started mid-run
    resumed = [e for e in ev if e["ev"] == "checkpoint_resumed"]
    assert resumed and resumed[0]["data"]["completed"] >= 16


def test_recovery_serves_completed_requests_from_journal(fx, tmp_path):
    """Requests that finished BEFORE the crash are answered from their
    journaled ``done`` record on the recovered server — zero recompute
    (no pack runs for them)."""
    jpath = str(tmp_path / "j.jsonl")
    srv, client = make_server(fx, tmp_path, journal=jpath)
    try:
        r1 = client.analyze("a", "d", "t", n_perm=32, seed=3,
                            idempotency_key="K", timeout=600)
    finally:
        srv.close(drain=True)
    # simulate the restart: fresh server, same journal
    srv2 = PreservationServer(ServeConfig(
        engine=CFG, journal=jpath, recover=True,
        telemetry=str(tmp_path / "tel_rec.jsonl"),
    ), start=False)   # worker never starts: nothing may need computing
    client2 = InProcessClient(srv2)
    try:
        r2 = client2.analyze("a", "d", "t", n_perm=32, seed=3,
                             idempotency_key="K", timeout=5)
        st = srv2.stats()
    finally:
        srv2.close(drain=False)
    np.testing.assert_array_equal(np.asarray(r1["p_values"]),
                                  np.asarray(r2["p_values"]))
    np.testing.assert_array_equal(np.asarray(r1["counts_hi"]),
                                  np.asarray(r2["counts_hi"]))
    assert st["packs"] == 0   # served purely from the journal
    ev = read_events(str(tmp_path / "tel_rec.jsonl"))
    assert [e["data"]["results"] for e in ev
            if e["ev"] == "journal_replayed"] == [1]


def test_kill_recover_trace_continuity_and_cost_conservation(fx, tmp_path):
    """ISSUE 13 acceptance across the crash: the client-minted trace id
    is present on the request's spans in BOTH server generations (the
    journal carries the trace context through ``--recover``),
    ``utils/trace.py`` merges the pre- and post-crash JSONL into ONE
    Perfetto trace under that id, and the recovered pack's attributed
    costs still sum bit-exactly to its totals."""
    from netrep_tpu.utils.trace import merge_events, render_perfetto

    jpath = str(tmp_path / "j.jsonl")
    ctx = {"trace": "ab" * 16, "parent": "client-span-9"}
    submits = [
        ("k1", dict(n_perm=64, seed=3, trace_ctx=ctx)),
        ("k2", dict(n_perm=64, seed=5)),
    ]
    srv1, handles = _crash_server(fx, tmp_path, jpath, "crash@24", submits,
                                  tel="tel_gen1")
    srv2 = PreservationServer(ServeConfig(
        engine=CFG, journal=jpath, recover=True, checkpoint_every=16,
        telemetry=str(tmp_path / "tel_gen2.jsonl"),
    ))
    client2 = InProcessClient(srv2)
    try:
        results = {
            k: client2.analyze(
                "a", "d", "t", idempotency_key=k, timeout=600,
                **{kk: v for kk, v in kw.items() if kk != "trace_ctx"},
            )
            for k, kw in submits
        }
    finally:
        srv2.close()
    # the recovered request still answers under the CLIENT's trace id
    assert results["k1"]["trace"] == ctx["trace"]
    # cost conservation on the checkpoint-resumed pack
    costs = [results[k]["cost"] for k, _ in submits]
    totals = costs[0]["pack_totals"]
    for f in ("device_s", "transfer_s", "perms", "bytes_to_host",
              "compile_s_amortized"):
        s = costs[0][f]
        for c in costs[1:]:
            s = s + c[f]
        assert s == totals[f], (f, s, totals[f])
    # the trace id is on the request spans of BOTH generations
    p1 = str(tmp_path / "tel_gen1.jsonl")
    p2 = str(tmp_path / "tel_gen2.jsonl")
    for p in (p1, p2):
        recv = [e for e in read_events(p) if e["ev"] == "request_received"]
        assert ctx["trace"] in {e["data"].get("trace") for e in recv}, p
    # merged export: every span carrying the trace id — from two
    # different runs/processes — lands under ONE pid (one continuous
    # trace), and run-namespaced span ids cannot collide
    trace_doc = render_perfetto(merge_events([p1, p2]))
    rows = [r for r in trace_doc["traceEvents"]
            if r.get("ph") == "X"
            and r.get("args", {}).get("trace") == ctx["trace"]]
    assert rows, "no spans carry the client trace id in the merged export"
    assert len({r["pid"] for r in rows}) == 1
    runs_of = {str(r["args"]["span"]).split(":", 1)[0] for r in rows}
    assert len(runs_of) == 2, "expected spans from both generations"
    # and the pid is named after the trace
    metas = [r for r in trace_doc["traceEvents"]
             if r.get("name") == "process_name"
             and r["pid"] == rows[0]["pid"]]
    assert metas and metas[0]["args"]["name"].startswith("trace ")


def test_journal_off_is_plain_pr7_serving(fx, tmp_path):
    """--no-journal / journal=None boots carry zero new machinery:
    no journal file, no checkpoint dir, results identical to direct."""
    srv, client = make_server(fx, tmp_path)
    try:
        res = client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
        assert srv.journal is None and srv._ckpt_dir is None
    finally:
        srv.close()
    d = direct(fx, n_perm=32, seed=3)
    np.testing.assert_array_equal(res["p_values"], np.asarray(d.p_values))
    assert not list(tmp_path.glob("*.ckpt"))


# ---------------------------------------------------------------------------
# deadline enforcement
# ---------------------------------------------------------------------------

def test_deadline_expiry_mid_pack_with_survivor_parity(fx, tmp_path):
    """One pack, two members: the short-deadline member is cancelled at a
    chunk boundary (request_expired, no result); its pack-mate finishes
    bit-identical to the direct call — retirement re-bucketing means a
    cancelled member just stops consuming dispatches."""
    srv, client = make_server(fx, tmp_path, start=False)
    h_ok = client.submit("a", "d", "t", n_perm=48, seed=3, deadline_s=600)
    # enormous budget + sub-compile-time deadline: expires at the first
    # boundary after the deadline passes, long before its ceiling
    h_exp = client.submit("a", "d", "t", n_perm=20000, seed=5,
                          deadline_s=0.2)
    srv.start()
    try:
        r_ok = client.result(h_ok, timeout=600)
        with pytest.raises(ServeError, match="deadline exceeded"):
            client.result(h_exp, timeout=600)
        st = srv.stats()
    finally:
        srv.close()
    assert r_ok["pack_size"] == 2          # they genuinely shared a pack
    d = direct(fx, n_perm=48, seed=3)
    np.testing.assert_array_equal(r_ok["observed"], d.observed)
    np.testing.assert_array_equal(r_ok["p_values"], np.asarray(d.p_values))
    assert st["tenants"]["a"]["expired"] == 1
    ev = read_events(str(tmp_path / "tel.jsonl"))
    exp = [e for e in ev if e["ev"] == "request_expired"]
    assert len(exp) == 1
    assert exp[0]["data"]["miss_s"] > 0 and exp[0]["data"]["folded"] > 0


def test_deadline_expired_in_queue_is_cancelled_before_dispatch(fx,
                                                                tmp_path):
    srv, client = make_server(fx, tmp_path, start=False)
    h = client.submit("a", "d", "t", n_perm=32, seed=3, deadline_s=0.0)
    time.sleep(0.05)
    srv.start()
    with pytest.raises(ServeError, match="deadline exceeded"):
        client.result(h, timeout=600)
    srv.close()
    ev = read_events(str(tmp_path / "tel.jsonl"))
    exp = [e for e in ev if e["ev"] == "request_expired"]
    assert exp and exp[0]["data"]["folded"] == 0
    # it never reached a pack
    assert not any(e["ev"] == "request_packed" for e in ev)


def test_enforce_deadlines_off_restores_sort_key_semantics(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, enforce_deadlines=False)
    try:
        res = client.analyze("a", "d", "t", n_perm=32, seed=3,
                             deadline_s=0.0, timeout=600)
    finally:
        srv.close()
    assert res["completed"] == 32          # PR 7: deadline never enforced


# ---------------------------------------------------------------------------
# brownout (overload shedding)
# ---------------------------------------------------------------------------

def test_brownout_enter_shed_exit_ordering(fx, tmp_path):
    """Enter past the drain-time threshold (event), shed the NEWEST
    requests of the LOWEST-weight tenant with a retry_after_s hint while
    heavier tenants stay admitted, exit with hysteresis once the queue
    drains (event) — enter strictly before exit, exactly one pair."""
    srv, client = make_server(
        fx, tmp_path, tenants=(), start=False,
        brownout_enter_s=1.0, brownout_rate_pps=10.0,
    )
    srv.register_tenant("hi", weight=2)
    srv.register_tenant("lo", weight=1)
    for t in ("hi", "lo"):
        client.register_dataset(t, "d", network=fx["dn"],
                                correlation=fx["dc"], data=fx["dd"],
                                assignments=fx["assign"])
        client.register_dataset(t, "t", network=fx["tn"],
                                correlation=fx["tc"], data=fx["td"])
    # 64 perms at an assumed 10 perms/s = 6.4s estimated drain > 1s
    h1 = client.submit("hi", "d", "t", n_perm=64, seed=1)
    assert srv.stats()["brownout"] is True
    with pytest.raises(QueueFull) as exc:
        client.submit("lo", "d", "t", n_perm=64, seed=2)
    assert exc.value.retry_after_s is not None
    assert exc.value.retry_after_s > 0
    h2 = client.submit("hi", "d", "t", n_perm=64, seed=3)  # weight 2: kept
    srv.start()
    try:
        client.result(h1, timeout=600)
        client.result(h2, timeout=600)
        st = srv.stats()
    finally:
        srv.close()
    assert st["brownout"] is False and st["tenants"]["lo"]["rejected"] == 1
    ev = read_events(str(tmp_path / "tel.jsonl"))
    names = [e["ev"] for e in ev if e["ev"].startswith("serve_brownout")]
    assert names == ["serve_brownout_enter", "serve_brownout_exit"]
    rej = [e for e in ev if e["ev"] == "request_rejected"]
    assert rej[0]["data"]["reason"] == "brownout"
    assert rej[0]["data"]["retry_after_s"] > 0


def test_brownout_off_by_default(fx, tmp_path):
    srv, client = make_server(fx, tmp_path, start=False)
    for i in range(4):
        client.submit("a", "d", "t", n_perm=64, seed=i)
    assert srv.stats()["brownout"] is False
    srv.close(drain=False)


# ---------------------------------------------------------------------------
# bounded drain (SIGTERM satellite)
# ---------------------------------------------------------------------------

def test_drain_timeout_journals_remainder_for_restart(fx, tmp_path):
    jpath = str(tmp_path / "j.jsonl")
    srv, client = make_server(fx, tmp_path, start=False, journal=jpath)
    h = client.submit("a", "d", "t", n_perm=32, seed=3,
                      idempotency_key="K")
    # the worker never starts: the bounded drain cannot finish the queue
    srv.close(drain=True, timeout=0.05)
    assert srv._last_drain_requeued == 1
    with pytest.raises(ServeError, match="journaled as requeued"):
        client.result(h, timeout=1)
    state = jnl.scan(jpath)
    assert [r["key"] for r in state["pending"]] == ["K"]
    assert state["n_drain_requeued"] == 1
    # the next --recover boot completes the journaled remainder
    srv2 = PreservationServer(ServeConfig(
        engine=CFG, journal=jpath, recover=True,
        telemetry=str(tmp_path / "tel2.jsonl"),
    ))
    client2 = InProcessClient(srv2)
    try:
        res = client2.analyze("a", "d", "t", n_perm=32, seed=3,
                              idempotency_key="K", timeout=600)
    finally:
        srv2.close()
    d = direct(fx, n_perm=32, seed=3)
    np.testing.assert_array_equal(res["p_values"], np.asarray(d.p_values))


# ---------------------------------------------------------------------------
# wire hardening (server.py satellite)
# ---------------------------------------------------------------------------

def test_wire_malformed_lines_keep_the_loop_alive(fx, tmp_path):
    import io

    from netrep_tpu.serve.server import (
        MAX_LINE_BYTES, dispatch_op, read_op_line,
    )

    srv, _client = make_server(fx, tmp_path, start=False)
    stop = threading.Event()
    lines = io.StringIO(
        "not json at all\n"
        "[1, 2, 3]\n"
        '{"op": "launch_missiles"}\n'
        '{"op": "ping"}\n'
    )
    responses = []
    while True:
        op, resp = read_op_line(lines, srv)
        if op is None and resp is None:
            break
        if resp is None:
            resp = dispatch_op(srv, op, stop)
        responses.append(resp)
    srv.close(drain=False)
    assert [r["ok"] for r in responses] == [False, False, False, True]
    assert responses[0]["malformed"] and "bad JSON" in responses[0]["error"]
    assert responses[1]["malformed"]          # non-object op
    assert "unknown op" in responses[2]["error"]
    assert responses[3]["pong"] is True       # the loop survived it all
    ev = read_events(str(tmp_path / "tel.jsonl"))
    assert sum(1 for e in ev if e["ev"] == "request_malformed") == 3


def test_wire_oversized_line_is_rejected_and_drained(fx, tmp_path,
                                                     monkeypatch):
    import io

    from netrep_tpu.serve import server as srv_mod

    monkeypatch.setattr(srv_mod, "MAX_LINE_BYTES", 64)
    srv, _client = make_server(fx, tmp_path, start=False)
    lines = io.StringIO('{"op": "ping", "junk": "' + "x" * 500 + '"}\n'
                        '{"op": "ping"}\n')
    op, resp = srv_mod.read_op_line(lines, srv)
    assert op is None and resp["malformed"]
    assert "exceeds" in resp["error"]
    # the oversized line was fully drained: the NEXT line parses cleanly
    op, resp = srv_mod.read_op_line(lines, srv)
    srv.close(drain=False)
    assert resp is None and op == {"op": "ping"}


def test_queue_full_wire_response_is_retryable_with_hint(fx, tmp_path):
    from netrep_tpu.serve.server import dispatch_op

    srv, client = make_server(fx, tmp_path, start=False, max_queue=1,
                              brownout_rate_pps=10.0)
    client.submit("a", "d", "t", n_perm=64, seed=1)
    resp = dispatch_op(srv, {"op": "analyze", "tenant": "a",
                             "discovery": "d", "test": "t",
                             "n_perm": 64, "seed": 2},
                       threading.Event())
    srv.close(drain=False)
    assert resp["ok"] is False and resp["retryable"] is True
    assert resp["retry_after_s"] > 0
    assert "QueueFull" in resp["error"]


# ---------------------------------------------------------------------------
# client retry-with-backoff
# ---------------------------------------------------------------------------

def test_retry_delay_deterministic_jitter():
    # the faults.py convention: (token, attempt) fully determine the delay
    assert retry_delay(1, "k") == retry_delay(1, "k")
    assert retry_delay(1, "k") != retry_delay(1, "other")
    d1, d2, d3 = (retry_delay(a, "k", jitter=0.0) for a in (1, 2, 3))
    assert d1 < d2 < d3 and d2 == 2 * d1      # exponential, no jitter
    assert retry_delay(10, "k", max_s=1.5, jitter=0.0) == 1.5


def test_client_retry_attaches_to_one_computation(fx, tmp_path):
    """A QueueFull'd analyze retried by the client under one idempotency
    key lands on exactly ONE computation once admitted."""

    class FlakyAdmission:
        """Server proxy whose submit rejects the first two attempts."""

        def __init__(self, server):
            self.server = server
            self.rejections = 0

        def analyze(self, tenant, discovery, test, timeout=None, **kw):
            if self.rejections < 2:
                self.rejections += 1
                raise QueueFull("synthetic overload", retry_after_s=0.01)
            return self.server.analyze(tenant, discovery, test,
                                       timeout=timeout, **kw)

    srv, _client = make_server(fx, tmp_path)
    proxy = InProcessClient(FlakyAdmission(srv))
    sleeps = []
    try:
        res = proxy.analyze("a", "d", "t", n_perm=32, seed=3,
                            retries=3, retry_base_s=0.0,
                            sleep=sleeps.append, timeout=600)
        st = srv.stats()
    finally:
        srv.close()
    assert res["completed"] == 32
    assert len(sleeps) == 2 and all(s >= 0.01 for s in sleeps)
    assert st["tenants"]["a"]["received"] == 1   # one admitted computation
    d = direct(fx, n_perm=32, seed=3)
    np.testing.assert_array_equal(res["p_values"], np.asarray(d.p_values))


# ---------------------------------------------------------------------------
# fault-plan surface for the drills
# ---------------------------------------------------------------------------

def test_crash_and_sigkill_plan_kinds_parse():
    specs = parse_plan("crash@24;sigkill@64x1")
    assert [(s.kind, s.at_perm) for s in specs] == [
        ("crash", 24), ("sigkill", 64),
    ]
    with pytest.raises(ValueError):
        parse_plan("explode@3")
