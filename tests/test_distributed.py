"""Multi-host initialization layer (SURVEY.md §2.3/§5 "Distributed
communication backend"). Real multi-process runs need multiple hosts; these
tests pin the single-process semantics (the common case) and the
configuration-validation contract, which is what can regress silently."""

import numpy as np
import pytest

import jax

from netrep_tpu.parallel import distributed


def test_single_process_defaults():
    assert distributed.is_initialized() is False
    info = distributed.initialize()  # no config, no cluster → single-process
    assert info["process_id"] == 0
    assert info["process_count"] == 1
    assert info["global_device_count"] == jax.device_count()
    # idempotent
    assert distributed.initialize() == info


def test_partial_config_rejected(monkeypatch):
    for var in distributed.ENV_VARS.values():
        monkeypatch.delenv(var, raising=False)
    with pytest.raises(ValueError, match="partial multi-host configuration"):
        distributed.initialize(coordinator_address="10.0.0.1:1234")
    with pytest.raises(ValueError, match="partial multi-host configuration"):
        distributed.initialize(num_processes=4, process_id=0)


def test_env_vars_complete_partial_args(monkeypatch):
    """Env vars fill in omitted args; a then-complete-but-bogus config must
    reach jax.distributed.initialize and surface its failure (not be
    silently swallowed like the no-config case)."""
    monkeypatch.setenv(distributed.ENV_VARS["num_processes"], "2")
    monkeypatch.setenv(distributed.ENV_VARS["process_id"], "0")
    with pytest.raises(Exception):
        # unroutable coordinator + tiny timeout → fails fast; the point is
        # that it was NOT treated as "no multi-host environment"
        distributed.initialize(
            coordinator_address="127.0.0.1:1", initialization_timeout=1
        )


def test_gather_to_host_single_process():
    x = jax.numpy.arange(12.0).reshape(3, 4)
    out = distributed.gather_to_host(x)
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, np.arange(12.0).reshape(3, 4))
