"""Thread-hygiene regression tests (ISSUE 12): the dynamic counterpart
of the ``thread-shared-state`` lint rule.

The package spawns helper threads in several places — the stall
watchdog, the async checkpoint writer, the serve worker, fault-runtime
dispatch threads — and every one of them is supposed to be joined or
stopped when its owner finishes. A leaked thread is a slow fleet killer:
each served request or preservation run that leaks one grows the
process until the scheduler drowns. These tests snapshot the live
Python thread set, run the thread-spawning paths end to end, and assert
the set RETURNS TO BASELINE (deliberately-leaked abandoned-dispatch
threads excepted — they are documented as unjoinable and only exist
when a dispatch actually hangs, which these runs never do)."""

import threading
import time

import numpy as np
import pytest

from netrep_tpu import module_preservation
from netrep_tpu.data import make_mixed_pair
from netrep_tpu.utils.config import EngineConfig, FaultPolicy


def _live():
    return {t for t in threading.enumerate() if t.is_alive()}


def _settle(baseline, timeout_s=15.0):
    """Wait for every non-baseline thread to exit; returns the leftovers
    (empty set = clean). Daemon helpers are joined by their owners, but
    the join happens-before the owner's return only up to a bounded
    timeout, so poll briefly instead of asserting instantly."""
    deadline = time.monotonic() + timeout_s
    extra = _live() - baseline
    while extra and time.monotonic() < deadline:
        time.sleep(0.05)
        extra = _live() - baseline
    return extra


@pytest.fixture()
def pair_kw():
    mixed = make_mixed_pair(100, 3, n_samples=16, seed=7)
    (dd, dc, dn), (td, tc, tn) = mixed["discovery"], mixed["test"]
    assign = {f"node_{i}": "0" for i in range(dn.shape[0])}
    for lab, idx in mixed["specs"]:
        for i in idx:
            assign[f"node_{i}"] = str(lab)
    return dict(
        network={"d": dn, "t": tn}, correlation={"d": dc, "t": tc},
        data={"d": dd, "t": td}, module_assignments=assign,
        discovery="d", test="t",
        config=EngineConfig(chunk_size=16, autotune=False),
    )


def test_preservation_run_releases_all_threads(pair_kw, tmp_path):
    """module_preservation with an active fault policy (stall watchdog +
    fault runtime) and a checkpoint path (async checkpoint writer) must
    return the process to its baseline thread set — no leaked
    netrep-stall-watchdog / netrep-ckpt-writer / netrep-ft-dispatch
    threads."""
    # warm-up absorbs lazily-created long-lived threads (XLA pools,
    # telemetry globals) so the baseline is what steady state looks like
    module_preservation(**pair_kw, n_perm=16, seed=0)
    baseline = _live()

    res = module_preservation(
        **pair_kw, n_perm=32, seed=0,
        telemetry=str(tmp_path / "tel.jsonl"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=16,
        fault_policy=FaultPolicy(backoff_base_s=0.0, backoff_jitter=0.0),
    )
    assert int(res.completed) == 32
    leftovers = _settle(baseline)
    assert not leftovers, (
        f"leaked threads after module_preservation: "
        f"{sorted(t.name for t in leftovers)}"
    )


def test_serve_drain_releases_all_threads(pair_kw, tmp_path):
    """Boot the in-process server, serve one request, drain — the serve
    worker, its watchdogs, and the pack machinery must all be gone when
    close(drain=True) returns."""
    from netrep_tpu.serve import InProcessClient, PreservationServer, \
        ServeConfig

    # warm-up: one full server lifecycle absorbs lazy singletons
    srv0 = PreservationServer(
        ServeConfig(engine=pair_kw["config"]), start=True)
    srv0.close(drain=False)
    baseline = _live()

    srv = PreservationServer(
        ServeConfig(engine=pair_kw["config"],
                    telemetry=str(tmp_path / "serve_tel.jsonl")),
        start=True,
    )
    client = InProcessClient(srv)
    client.register_dataset("a", "d", network=pair_kw["network"]["d"],
                            correlation=pair_kw["correlation"]["d"],
                            data=pair_kw["data"]["d"],
                            assignments=pair_kw["module_assignments"])
    client.register_dataset("a", "t", network=pair_kw["network"]["t"],
                            correlation=pair_kw["correlation"]["t"],
                            data=pair_kw["data"]["t"])
    res = client.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
    assert np.asarray(res["p_values"]).size
    srv.close(drain=True)

    leftovers = _settle(baseline)
    assert not leftovers, (
        f"leaked threads after serve drain: "
        f"{sorted(t.name for t in leftovers)}"
    )


def test_fleet_drain_releases_all_threads(pair_kw, tmp_path):
    """ISSUE 14: the fleet coordinator spawns a health-loop thread plus
    one journal-shipper thread per replica on top of each replica's
    serve worker — after close(drain=True) the process must return to
    its baseline thread set (no leaked netrep-fleet-health /
    netrep-journal-shipper / netrep-serve-worker threads)."""
    from netrep_tpu.serve import FleetConfig, ServeConfig, \
        build_inprocess_fleet

    def mk(rid, jpath, ckpt):
        return ServeConfig(engine=pair_kw["config"], journal=jpath,
                           checkpoint_dir=ckpt)

    # warm-up: one full fleet lifecycle absorbs lazy singletons
    fleet0 = build_inprocess_fleet(
        2, str(tmp_path / "warm"), make_config=mk,
        fleet_config=FleetConfig(heartbeat_s=0.1),
    )
    fleet0.close(drain=False)
    baseline = _live()

    fleet = build_inprocess_fleet(
        2, str(tmp_path / "fleet"), make_config=mk,
        fleet_config=FleetConfig(
            heartbeat_s=0.1,
            telemetry=str(tmp_path / "fleet_tel.jsonl"),
        ),
    )
    fleet.register_dataset("a", "d", network=pair_kw["network"]["d"],
                           correlation=pair_kw["correlation"]["d"],
                           data=pair_kw["data"]["d"],
                           assignments=pair_kw["module_assignments"])
    fleet.register_dataset("a", "t", network=pair_kw["network"]["t"],
                           correlation=pair_kw["correlation"]["t"],
                           data=pair_kw["data"]["t"])
    res = fleet.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
    assert np.asarray(res["p_values"]).size
    fleet.close(drain=True)

    leftovers = _settle(baseline)
    assert not leftovers, (
        f"leaked threads after fleet drain: "
        f"{sorted(t.name for t in leftovers)}"
    )


def test_autoscale_cycle_releases_all_threads(pair_kw, tmp_path):
    """ISSUE 19: the autoscaler adds its own control-loop thread
    (netrep-fleet-autoscale) on top of the fleet's, and a scale-down
    retirement drains a whole replica (worker + shipper) mid-session —
    after one live autoscale cycle (serve, idle, retire down to the
    floor) and close(drain=True), the process must return to its
    baseline thread set."""
    from netrep_tpu.serve import AutoscaleConfig, Autoscaler, \
        FleetConfig, ServeConfig, build_inprocess_fleet, \
        inprocess_spawner

    def mk(rid, jpath, ckpt):
        return ServeConfig(engine=pair_kw["config"], journal=jpath,
                           checkpoint_dir=ckpt)

    # warm-up: one full fleet lifecycle absorbs lazy singletons
    fleet0 = build_inprocess_fleet(
        2, str(tmp_path / "warm"), make_config=mk,
        fleet_config=FleetConfig(heartbeat_s=0.1),
    )
    fleet0.close(drain=False)
    baseline = _live()

    fleet = build_inprocess_fleet(
        2, str(tmp_path / "fleet"), make_config=mk,
        fleet_config=FleetConfig(
            heartbeat_s=0.1,
            telemetry=str(tmp_path / "fleet_tel.jsonl"),
        ),
    )
    Autoscaler(
        fleet, inprocess_spawner(str(tmp_path / "fleet"), make_config=mk),
        AutoscaleConfig(scale_down_idle_s=0.5, cooldown_s=0.1,
                        tick_s=0.05, min_replicas=1, max_replicas=2),
    )
    fleet.register_dataset("a", "d", network=pair_kw["network"]["d"],
                           correlation=pair_kw["correlation"]["d"],
                           data=pair_kw["data"]["d"],
                           assignments=pair_kw["module_assignments"])
    fleet.register_dataset("a", "t", network=pair_kw["network"]["t"],
                           correlation=pair_kw["correlation"]["t"],
                           data=pair_kw["data"]["t"])
    res = fleet.analyze("a", "d", "t", n_perm=32, seed=3, timeout=600)
    assert np.asarray(res["p_values"]).size
    # the loop notices the idle fleet and retires down to the floor —
    # a live mid-session drain of one replica's worker + shipper
    deadline = time.monotonic() + 60
    while (len(fleet.live_replicas()) > 1
           and time.monotonic() < deadline):
        time.sleep(0.05)
    assert len(fleet.live_replicas()) == 1
    fleet.close(drain=True)   # stops the autoscaler thread first

    leftovers = _settle(baseline)
    assert not leftovers, (
        f"leaked threads after autoscale cycle: "
        f"{sorted(t.name for t in leftovers)}"
    )
