"""Oracle-parity tests: JAX masked kernels vs the pure-NumPy oracle
(SURVEY.md §4 — the reference cross-checks its C++ kernels against slow
pure-R re-implementations; we do the same with NumPy vs JAX)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from netrep_tpu.ops import oracle
from netrep_tpu.ops import stats as jstats


def _random_module(rng, m=17, ns_d=30, ns_t=25, n_test=60):
    """Random discovery module + test matrices with planted correlation so
    the top singular value is well separated (fast power-iteration parity)."""
    latent_d = rng.standard_normal(ns_d)
    latent_t = rng.standard_normal(ns_t)
    d_data = 0.8 * np.outer(latent_d, rng.choice([-1, 1], m)) + 0.6 * rng.standard_normal((ns_d, m))
    d_corr = np.corrcoef(d_data, rowvar=False)
    d_net = np.abs(d_corr) ** 2

    t_data = 0.8 * np.outer(latent_t, rng.choice([-1, 1], n_test)) + 0.6 * rng.standard_normal((ns_t, n_test))
    t_corr = np.corrcoef(t_data, rowvar=False)
    t_net = np.abs(t_corr) ** 2
    idx = rng.choice(n_test, size=m, replace=False)
    return d_data, d_corr, d_net, t_data, t_corr, t_net, idx


def _pad(a, cap, axis=-1):
    pad = [(0, 0)] * a.ndim
    pad[axis] = (0, cap - a.shape[axis])
    return np.pad(a, pad)


def _padded_disc(d_corr, d_net, d_data, m, cap, summary_method="eigh"):
    mask = np.zeros(cap, dtype=np.float32)
    mask[:m] = 1.0
    corr_p = _pad(_pad(d_corr, cap, -1), cap, -2)
    net_p = _pad(_pad(d_net, cap, -1), cap, -2)
    data_p = _pad(d_data, cap, -1) if d_data is not None else None
    disc = jstats.make_disc_props(corr_p, net_p, data_p, mask, summary_method=summary_method)
    return disc, mask


@pytest.mark.parametrize("cap_extra", [0, 7])
def test_module_stats_match_oracle(rng, cap_extra):
    """Seven statistics match the oracle, with and without padding."""
    d_data, d_corr, d_net, t_data, t_corr, t_net, idx = _random_module(rng)
    m = len(idx)
    cap = m + cap_extra

    sub = np.ix_(idx, idx)
    disc_o = oracle.DiscoveryProps(d_corr, d_net, d_data)
    expected = oracle.module_stats(disc_o, t_corr[sub], t_net[sub], t_data[:, idx])

    disc, mask = _padded_disc(d_corr, d_net, d_data, m, cap)
    idx_p = _pad(idx.astype(np.int32), cap)
    got = jstats.gather_and_stats(
        disc, jnp.asarray(idx_p), jnp.asarray(t_corr, jnp.float32),
        jnp.asarray(t_net, jnp.float32), jnp.asarray(t_data.T, jnp.float32),
        summary_method="eigh",
    )
    np.testing.assert_allclose(np.asarray(got), expected, rtol=0, atol=5e-5)


def test_dataless_variant(rng):
    """Without data only avg.weight / cor.cor / cor.degree are finite
    (SURVEY.md §2.2 data-less case)."""
    d_data, d_corr, d_net, t_data, t_corr, t_net, idx = _random_module(rng)
    m = len(idx)
    sub = np.ix_(idx, idx)
    disc_o = oracle.DiscoveryProps(d_corr, d_net, None)
    expected = oracle.module_stats(disc_o, t_corr[sub], t_net[sub], None)

    finite = ~np.isnan(expected)
    assert [oracle.STAT_NAMES[i] for i in np.where(finite)[0]] == list(oracle.TOPOLOGY_STATS)

    disc, mask = _padded_disc(d_corr, d_net, None, m, m + 3)
    idx_p = _pad(idx.astype(np.int32), m + 3)
    got = np.asarray(jstats.gather_and_stats(
        disc, jnp.asarray(idx_p), jnp.asarray(t_corr, jnp.float32),
        jnp.asarray(t_net, jnp.float32), None))
    np.testing.assert_allclose(got[finite], expected[finite], atol=2e-5)
    assert np.isnan(got[~finite]).all()


def test_power_iteration_matches_eigh(rng):
    """Masked power iteration converges to the exact summary profile on
    planted-structure data (SURVEY.md §7 'Batched SVD on TPU' risk item)."""
    d_data, *_ = _random_module(rng, m=24)
    cap = 30
    mask = np.zeros(cap, dtype=np.float32)
    mask[:24] = 1.0
    z = jstats.standardize_masked(jnp.asarray(_pad(d_data, cap), jnp.float32), jnp.asarray(mask))
    p_power = np.asarray(jstats.summary_profile_masked(z, jnp.asarray(mask), n_iter=100, method="power"))
    p_eigh = np.asarray(jstats.summary_profile_masked(z, jnp.asarray(mask), method="eigh"))
    np.testing.assert_allclose(p_power, p_eigh, atol=1e-4)

    p_oracle = oracle.summary_profile(d_data)
    np.testing.assert_allclose(p_eigh, p_oracle, atol=1e-4)


def test_building_blocks_match_oracle(rng):
    d_data, d_corr, d_net, *_ = _random_module(rng, m=13)
    cap = 16
    mask = np.zeros(cap, dtype=np.float32)
    mask[:13] = 1.0

    deg = np.asarray(jstats.weighted_degree_masked(
        jnp.asarray(_pad(_pad(d_net, cap, -1), cap, -2), jnp.float32), jnp.asarray(mask)))
    np.testing.assert_allclose(deg[:13], oracle.weighted_degree(d_net), atol=1e-5)
    assert (deg[13:] == 0).all()

    z = jstats.standardize_masked(jnp.asarray(_pad(d_data, cap), jnp.float32), jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(z)[:, :13], oracle.standardize(d_data), atol=2e-5)

    prof = jstats.summary_profile_masked(z, jnp.asarray(mask), method="eigh")
    nc = np.asarray(jstats.node_contribution_masked(z, prof, jnp.asarray(mask)))
    np.testing.assert_allclose(nc[:13], oracle.node_contribution(d_data), atol=1e-4)

    coh = float(jstats.masked_mean(jnp.asarray(nc) ** 2, jnp.asarray(mask)))
    assert abs(coh - oracle.module_coherence(d_data)) < 1e-4


def test_masked_pearson_degenerate():
    x = jnp.asarray([1.0, 1.0, 1.0, 0.0])
    y = jnp.asarray([1.0, 2.0, 3.0, 0.0])
    w = jnp.asarray([1.0, 1.0, 1.0, 0.0])
    assert np.isnan(float(jstats.masked_pearson(x, y, w)))


def test_vmap_over_permutations(rng):
    """The kernel composes with vmap over many index sets — the reference's
    OpenMP permutation loop axis (SURVEY.md §2.3) as a batched XLA op."""
    d_data, d_corr, d_net, t_data, t_corr, t_net, _ = _random_module(rng)
    m, cap, nperm = 17, 20, 8
    disc, mask = _padded_disc(d_corr, d_net, d_data, m, cap)

    idx_batch = np.zeros((nperm, cap), dtype=np.int32)
    for p in range(nperm):
        idx_batch[p, :m] = rng.choice(t_corr.shape[0], size=m, replace=False)

    fn = jax.vmap(lambda ix: jstats.gather_and_stats(
        disc, ix, jnp.asarray(t_corr, jnp.float32), jnp.asarray(t_net, jnp.float32),
        jnp.asarray(t_data.T, jnp.float32), summary_method="eigh"))
    got = np.asarray(fn(jnp.asarray(idx_batch)))

    disc_o = oracle.DiscoveryProps(d_corr, d_net, d_data)
    for p in range(nperm):
        idx = idx_batch[p, :m]
        sub = np.ix_(idx, idx)
        expected = oracle.module_stats(disc_o, t_corr[sub], t_net[sub], t_data[:, idx])
        np.testing.assert_allclose(got[p], expected, atol=1e-4)


def test_module_stats_for_indices_data_less():
    """The shared reconstruction helper's data-less path: topology
    statistics computed, data-dependent ones NaN — same contract as
    module_stats (SURVEY.md §2.2 data-less case)."""
    rng = np.random.default_rng(23)
    n = 30
    x = rng.standard_normal((12, n))
    c = np.corrcoef(x, rowvar=False)
    net = np.abs(c) ** 2
    di = [np.arange(0, 8), np.arange(8, 20)]
    ti = [np.arange(5, 13), np.arange(13, 25)]
    out = oracle.module_stats_for_indices(
        c, net, None, c, net, None, di, ti,
    )
    assert out.shape == (2, 7)
    # avg.weight, cor.cor, cor.degree computable; the rest NaN
    computable = [0, 2, 3]
    assert np.isfinite(out[:, computable]).all()
    nan_stats = [i for i in range(7) if i not in computable]
    assert np.isnan(out[:, nan_stats]).all()
