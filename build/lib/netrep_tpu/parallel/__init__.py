"""Parallel execution: the vmap/jit permutation engine with optional
mesh-sharded chunks (SURVEY.md §2.3 parallelism table)."""
