"""User-facing model layer: dataset containers, the `module_preservation`
orchestrator, `network_properties`, and result shaping (SURVEY.md §2.1)."""
