"""`network_properties` — observed per-module topological properties, the
rebuild of the reference's ``networkProperties()`` / NetProps C++ entry
(SURVEY.md §2.1, §3.2): per dataset and module, the summary profile
(eigengene), weighted degree, node contribution, coherence, and average edge
weight; the data-less variant skips the data-dependent properties.

These are one-shot observed computations (once per module, not the hot
loop), so they run through the NumPy oracle kernels — the framework's
semantic source of truth (netrep_tpu/ops/oracle.py), against which the JAX
hot-path kernels are parity-tested. Device dispatch would add latency, not
throughput, here.
"""

from __future__ import annotations

import numpy as np

from ..ops import oracle
from . import dataset as ds


def network_properties(
    network,
    data=None,
    correlation=None,
    module_assignments=None,
    modules=None,
    background_label: str = "0",
    discovery=None,
    test=None,
    self_preservation: bool = True,
    simplify: bool = True,
):
    """Observed per-module network properties (SURVEY.md §3.2).

    Returns ``{discovery: {test: {module: props}}}`` where ``props`` has:

    - ``summary`` : (n_samples,) summary profile (None when data-less)
    - ``degree`` : (m,) within-module weighted degree, normalized to the
      module maximum
    - ``contribution`` : (m,) node contributions (None when data-less)
    - ``coherence`` : float (NaN when data-less)
    - ``avg_weight`` : float
    - ``node_names`` : module node labels present in the dataset

    ``simplify=True`` collapses single-level nesting (reference semantics,
    SURVEY.md §2.1).
    """
    datasets = ds.build_datasets(network, data=data, correlation=correlation)
    # networkProperties defaults to computing properties in every dataset,
    # including the discovery itself (self pairs allowed).
    pairs = ds.resolve_pairs(datasets, discovery, test, self_preservation)
    disc_names = sorted({d for d, _ in pairs}, key=list(datasets).index)
    assign = ds.normalize_module_assignments(
        module_assignments, datasets, disc_names
    )

    out: dict[str, dict[str, dict[str, dict]]] = {}
    for d_name, t_name in pairs:
        disc_ds, tgt = datasets[d_name], datasets[t_name]
        labels, specs, _counts = ds.module_overlap(
            disc_ds, tgt, assign[d_name], modules, background_label
        )
        per_mod = {}
        for lab, _di, ti in specs:
            if len(ti) == 0:
                per_mod[lab] = None
                continue
            sub = np.ix_(ti, ti)
            net_sub = tgt.network[sub]
            deg = oracle.weighted_degree(net_sub)
            dmax = np.max(np.abs(deg))
            props = {
                "node_names": [tgt.node_names[i] for i in ti],
                "degree": deg / dmax if dmax > 0 else deg,
                "avg_weight": oracle.avg_edge_weight(net_sub),
                "summary": None,
                "contribution": None,
                "coherence": float("nan"),
            }
            if tgt.data is not None:
                dat = tgt.data[:, ti]
                prof = oracle.summary_profile(dat)
                nc = oracle.node_contribution(dat, prof)
                props.update(
                    summary=prof,
                    contribution=nc,
                    coherence=float(np.mean(nc**2)),
                )
            per_mod[lab] = props
        out.setdefault(d_name, {})[t_name] = per_mod

    if simplify:
        if len(out) == 1:
            inner = next(iter(out.values()))
            return next(iter(inner.values())) if len(inner) == 1 else inner
    return out
