"""Build machinery for the native C++ compute core.

Compiles ``netstats.cpp`` with the system ``g++`` into a shared object the
first time it is needed, keyed by a hash of the source so edits invalidate
the cache automatically. Mirrors the role of the reference's ``src/Makevars``
build config (SURVEY.md §2.2 "Build config") without requiring users to run
a build step: the library is built lazily on first use and cached under the
package directory.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_HERE, "netstats.cpp")

CXX = os.environ.get("NETREP_CXX", "g++")
CXXFLAGS = [
    "-O3",
    "-std=c++17",
    "-shared",
    "-fPIC",
    "-pthread",
    "-fno-math-errno",
]


def _source_tag() -> str:
    with open(SOURCE, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:12]


def lib_path() -> str:
    return os.path.join(_HERE, f"_netstats_{_source_tag()}.so")


def toolchain_available() -> bool:
    try:
        subprocess.run(
            [CXX, "--version"], capture_output=True, check=True, timeout=30
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def ensure_built() -> str:
    """Compile the shared object if the cached build is missing; return its
    path. Raises ``RuntimeError`` with the compiler output on failure."""
    path = lib_path()
    if os.path.exists(path):
        return path
    # build into a temp file then atomically rename, so concurrent importers
    # (e.g. pytest-xdist workers) never load a half-written .so
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        proc = subprocess.run(
            [CXX, *CXXFLAGS, SOURCE, "-o", tmp],
            capture_output=True,
            text=True,
            timeout=300,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed ({CXX} exit {proc.returncode}):\n"
                f"{proc.stderr}"
            )
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path
