// netstats.cpp — native C++ compute core for netrep-tpu.
//
// This is the rebuild's equivalent of the reference's native tier
// (SURVEY.md §2.2): the seven module-preservation statistic kernels
// (reference: src/netStats.cpp) and the threaded permutation procedure
// (reference: src/permutations.cpp::PermutationProcedure over an OpenMP
// pool, BASELINE.json:5). The reference mount is empty (SURVEY.md §0), so
// definitions follow the framework's NumPy oracle
// (netrep_tpu/ops/oracle.py) exactly — oracle parity is the correctness
// contract, enforced by tests/test_native.py.
//
// Design (not a translation):
//   * C ABI (extern "C"), loaded from Python via ctypes — no Rcpp-style
//     generated glue, no R types.
//   * std::thread pool with an atomic work counter instead of OpenMP
//     pragmas; permutations own disjoint output slices, so writes are
//     lock-free by construction (same property the reference relies on).
//   * Per-permutation counter-based RNG seeding (splitmix64 of
//     seed ^ global permutation index) so results are independent of the
//     thread count and of how the caller chunks the permutation range —
//     the determinism contract SURVEY.md §4 asks tests to enforce.
//   * Summary profile via power iteration on the standardized data slice
//     (top left singular vector), matching the oracle's SVD + sign-anchor
//     semantics without a LAPACK dependency.
//   * Cooperative cancellation: workers poll a caller-owned flag
//     (the reference's Ctrl-C path, SURVEY.md §5); progress is an atomic
//     counter the caller may read concurrently.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread (netrep_tpu/native/build.py).

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <random>
#include <thread>
#include <vector>

namespace {

constexpr int N_STATS = 7;  // STAT_NAMES order, ops/oracle.py:51

// ---------------------------------------------------------------------------
// splitmix64 — seeds one mt19937_64 per (seed, permutation index)
// ---------------------------------------------------------------------------
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

// Unbiased bounded draw in [0, bound) via rejection sampling on the raw
// mt19937_64 stream. std::uniform_int_distribution is implementation-
// defined (libstdc++ and libc++ map the same generator stream to different
// values), which would break the advertised determinism contract across
// platforms — this fixed algorithm is part of the RNG spec.
inline uint64_t bounded_draw(std::mt19937_64& gen, uint64_t bound) {
  const uint64_t threshold = (~uint64_t{0} - bound + 1) % bound;  // 2^64 mod bound
  for (;;) {
    const uint64_t r = gen();
    if (r >= threshold) return r % bound;
  }
}

// ---------------------------------------------------------------------------
// statistic building blocks (oracle.py building blocks, SURVEY.md §2.2)
// ---------------------------------------------------------------------------

inline double sgn(double v) { return v > 0 ? 1.0 : (v < 0 ? -1.0 : 0.0); }

// mean off-diagonal edge weight (oracle.avg_edge_weight)
double avg_weight(const double* net, int m) {
  if (m < 2) return NAN;
  double total = 0.0, tr = 0.0;
  for (int i = 0; i < m; ++i) {
    const double* row = net + (size_t)i * m;
    tr += row[i];
    for (int j = 0; j < m; ++j) total += row[j];
  }
  return (total - tr) / ((double)m * (m - 1));
}

// within-module weighted degree: row sums, diagonal excluded
void weighted_degree(const double* net, int m, double* out) {
  for (int i = 0; i < m; ++i) {
    const double* row = net + (size_t)i * m;
    double s = 0.0;
    for (int j = 0; j < m; ++j) s += row[j];
    out[i] = s - row[i];
  }
}

// Pearson correlation of two length-n vectors; NaN when degenerate
double pearson(const double* x, const double* y, int n) {
  if (n < 2) return NAN;
  double mx = 0.0, my = 0.0;
  for (int i = 0; i < n; ++i) { mx += x[i]; my += y[i]; }
  mx /= n; my /= n;
  double sxy = 0.0, sxx = 0.0, syy = 0.0;
  for (int i = 0; i < n; ++i) {
    const double a = x[i] - mx, b = y[i] - my;
    sxy += a * b; sxx += a * a; syy += b * b;
  }
  const double denom = std::sqrt(sxx) * std::sqrt(syy);
  return denom == 0.0 ? NAN : sxy / denom;
}

// Pearson over the off-diagonal entries of two m×m matrices (cor.cor)
double pearson_offdiag(const double* a, const double* b, int m) {
  const long n = (long)m * m - m;
  if (n < 2) return NAN;
  double mx = 0.0, my = 0.0;
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < m; ++j)
      if (i != j) { mx += a[(size_t)i * m + j]; my += b[(size_t)i * m + j]; }
  mx /= n; my /= n;
  double sxy = 0.0, sxx = 0.0, syy = 0.0;
  for (int i = 0; i < m; ++i)
    for (int j = 0; j < m; ++j)
      if (i != j) {
        const double u = a[(size_t)i * m + j] - mx;
        const double v = b[(size_t)i * m + j] - my;
        sxy += u * v; sxx += u * u; syy += v * v;
      }
  const double denom = std::sqrt(sxx) * std::sqrt(syy);
  return denom == 0.0 ? NAN : sxy / denom;
}

// Column-standardize (mean 0, sd 1 with ddof=1; zero-variance columns → 0),
// matching oracle.standardize. z is s×m row-major.
void standardize_cols(double* z, int s, int m) {
  for (int j = 0; j < m; ++j) {
    double mu = 0.0;
    for (int i = 0; i < s; ++i) mu += z[(size_t)i * m + j];
    mu /= s;
    double ss = 0.0;
    for (int i = 0; i < s; ++i) {
      const double d = z[(size_t)i * m + j] - mu;
      ss += d * d;
    }
    const double sd = s > 1 ? std::sqrt(ss / (s - 1)) : 0.0;
    if (sd > 0.0) {
      const double inv = 1.0 / sd;
      for (int i = 0; i < s; ++i)
        z[(size_t)i * m + j] = (z[(size_t)i * m + j] - mu) * inv;
    } else {
      for (int i = 0; i < s; ++i) z[(size_t)i * m + j] = 0.0;
    }
  }
}

// Summary profile (oracle.summary_profile): top left singular vector of the
// standardized s×m slice via power iteration on Z Zᵀ (applied as Z(Zᵀv) so
// no Gram matrix is formed), sign-anchored to the mean node profile.
// prof (s), tmp (m) are caller scratch. z must already be standardized.
void summary_profile(const double* z, int s, int m, double* prof, double* tmp) {
  // anchor = row means of Z — also the power-iteration start (it has a
  // healthy overlap with the top singular direction in practice)
  std::vector<double> anchor(s);
  for (int i = 0; i < s; ++i) {
    double a = 0.0;
    const double* row = z + (size_t)i * m;
    for (int j = 0; j < m; ++j) a += row[j];
    anchor[i] = a / (m > 0 ? m : 1);
  }
  double an = 0.0;
  for (int i = 0; i < s; ++i) an += anchor[i] * anchor[i];
  if (an > 0.0) {
    const double inv = 1.0 / std::sqrt(an);
    for (int i = 0; i < s; ++i) prof[i] = anchor[i] * inv;
  } else {
    // degenerate anchor: deterministic unit start
    for (int i = 0; i < s; ++i) prof[i] = 0.0;
    prof[0] = 1.0;
  }

  std::vector<double> next(s);
  for (int iter = 0; iter < 512; ++iter) {
    // tmp = Zᵀ prof  (m)
    for (int j = 0; j < m; ++j) tmp[j] = 0.0;
    for (int i = 0; i < s; ++i) {
      const double v = prof[i];
      const double* row = z + (size_t)i * m;
      for (int j = 0; j < m; ++j) tmp[j] += row[j] * v;
    }
    // next = Z tmp  (s)
    double nrm = 0.0;
    for (int i = 0; i < s; ++i) {
      const double* row = z + (size_t)i * m;
      double a = 0.0;
      for (int j = 0; j < m; ++j) a += row[j] * tmp[j];
      next[i] = a;
      nrm += a * a;
    }
    nrm = std::sqrt(nrm);
    if (nrm == 0.0) break;  // Z ≡ 0: keep start vector (contribs are 0 anyway)
    double delta = 0.0;
    const double inv = 1.0 / nrm;
    for (int i = 0; i < s; ++i) {
      const double v = next[i] * inv;
      const double d = v - prof[i];
      delta += d * d;
      prof[i] = v;
    }
    if (delta < 1e-26) break;
  }
  // sign anchor (oracle: positive correlation with the mean node profile)
  double dot = 0.0;
  for (int i = 0; i < s; ++i) dot += prof[i] * anchor[i];
  if (dot < 0.0)
    for (int i = 0; i < s; ++i) prof[i] = -prof[i];
}

// Node contribution (oracle.node_contribution): cor(node column, profile)
void node_contribution(const double* z, int s, int m, const double* prof,
                       double* out) {
  double pm = 0.0;
  for (int i = 0; i < s; ++i) pm += prof[i];
  pm /= (s > 0 ? s : 1);
  std::vector<double> pc(s);
  double pn = 0.0;
  for (int i = 0; i < s; ++i) { pc[i] = prof[i] - pm; pn += pc[i] * pc[i]; }
  pn = std::sqrt(pn);
  for (int j = 0; j < m; ++j) {
    double dot = 0.0, xn = 0.0;
    for (int i = 0; i < s; ++i) {
      const double v = z[(size_t)i * m + j];
      dot += v * pc[i];
      xn += v * v;
    }
    const double denom = pn * std::sqrt(xn);
    out[j] = denom == 0.0 ? 0.0 : dot / denom;
  }
}

// ---------------------------------------------------------------------------
// per-module discovery-side fixed properties (oracle.DiscoveryProps)
// ---------------------------------------------------------------------------
struct DiscModule {
  const double* corr;     // m×m discovery correlation submatrix
  const double* degree;   // m
  const double* contrib;  // m, or nullptr when data-less
  int m;
};

struct Scratch {
  std::vector<double> corr, net, z, deg, contrib, prof, tmp;
  std::vector<int> perm;
  void reserve(int max_m, int s, int pool) {
    corr.resize((size_t)max_m * max_m);
    net.resize((size_t)max_m * max_m);
    z.resize((size_t)(s > 0 ? s : 1) * max_m);
    deg.resize(max_m);
    contrib.resize(max_m);
    prof.resize(s > 0 ? s : 1);
    tmp.resize(max_m);
    perm.resize(pool);
  }
};

// The seven statistics for one candidate test-side node set against fixed
// discovery properties (oracle.module_stats). idx holds d.m test indices.
void module_stats(const DiscModule& d, const double* tcorr,
                  const double* tnet, const double* tdata, int n, int s,
                  const int* idx, Scratch& sc, double* out) {
  const int m = d.m;
  // O(m²) gather out of the n×n matrices — the hot access pattern
  // (SURVEY.md §3.1 hot loop)
  for (int i = 0; i < m; ++i) {
    const double* crow = tcorr + (size_t)idx[i] * n;
    const double* nrow = tnet + (size_t)idx[i] * n;
    double* ci = sc.corr.data() + (size_t)i * m;
    double* ni = sc.net.data() + (size_t)i * m;
    for (int j = 0; j < m; ++j) {
      ci[j] = crow[idx[j]];
      ni[j] = nrow[idx[j]];
    }
  }
  for (int k = 0; k < N_STATS; ++k) out[k] = NAN;
  out[0] = avg_weight(sc.net.data(), m);
  out[2] = pearson_offdiag(d.corr, sc.corr.data(), m);
  weighted_degree(sc.net.data(), m, sc.deg.data());
  out[3] = pearson(d.degree, sc.deg.data(), m);

  if (tdata != nullptr && d.contrib != nullptr && s > 0) {
    // gather data columns → z (s×m), standardize, profile, contributions
    for (int i = 0; i < s; ++i) {
      const double* drow = tdata + (size_t)i * n;
      double* zrow = sc.z.data() + (size_t)i * m;
      for (int j = 0; j < m; ++j) zrow[j] = drow[idx[j]];
    }
    standardize_cols(sc.z.data(), s, m);
    summary_profile(sc.z.data(), s, m, sc.prof.data(), sc.tmp.data());
    node_contribution(sc.z.data(), s, m, sc.prof.data(), sc.contrib.data());

    double coh = 0.0, ac = 0.0;
    for (int j = 0; j < m; ++j) {
      coh += sc.contrib[j] * sc.contrib[j];
      ac += sgn(d.contrib[j]) * sc.contrib[j];
    }
    out[1] = m > 0 ? coh / m : NAN;                       // coherence
    out[4] = pearson(d.contrib, sc.contrib.data(), m);    // cor.contrib
    // avg.cor: sign-aware mean over off-diagonal pairs (discovery signs)
    double sum = 0.0;
    const long cnt = (long)m * m - m;
    for (int i = 0; i < m; ++i)
      for (int j = 0; j < m; ++j)
        if (i != j)
          sum += sgn(d.corr[(size_t)i * m + j]) * sc.corr[(size_t)i * m + j];
    out[5] = cnt > 0 ? sum / cnt : NAN;
    out[6] = m > 0 ? ac / m : NAN;                        // avg.contrib
  }
}

std::vector<DiscModule> make_disc(const double* dcorr_cat,
                                  const double* ddeg_cat,
                                  const double* dcontrib_cat,
                                  const int* sizes, int n_mod) {
  std::vector<DiscModule> disc(n_mod);
  size_t coff = 0, voff = 0;
  for (int k = 0; k < n_mod; ++k) {
    const int m = sizes[k];
    disc[k].corr = dcorr_cat + coff;
    disc[k].degree = ddeg_cat + voff;
    disc[k].contrib = dcontrib_cat ? dcontrib_cat + voff : nullptr;
    disc[k].m = m;
    coff += (size_t)m * m;
    voff += m;
  }
  return disc;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// nr_observed — the observed pass (SURVEY.md §3.1 "observed pass"): per
// module, the explicit test-side index set → seven statistics.
//   idx_cat: concatenated test indices (sum of sizes)
//   out:     n_mod × 7, row-major
// ---------------------------------------------------------------------------
void nr_observed(const double* tcorr, const double* tnet, const double* tdata,
                 int n, int s, const int* idx_cat, const int* sizes, int n_mod,
                 const double* dcorr_cat, const double* ddeg_cat,
                 const double* dcontrib_cat, double* out) {
  auto disc = make_disc(dcorr_cat, ddeg_cat, dcontrib_cat, sizes, n_mod);
  int max_m = 1;
  for (int k = 0; k < n_mod; ++k) max_m = std::max(max_m, sizes[k]);
  Scratch sc;
  sc.reserve(max_m, s, 1);
  size_t off = 0;
  for (int k = 0; k < n_mod; ++k) {
    module_stats(disc[k], tcorr, tnet, tdata, n, s, idx_cat + off, sc,
                 out + (size_t)k * N_STATS);
    off += sizes[k];
  }
}

// ---------------------------------------------------------------------------
// nr_null — the permutation procedure (reference PermutationProcedure,
// SURVEY.md §2.2/§3.1): for global permutation indices
// [perm_offset, perm_offset + n_perm), draw one pool permutation, assign
// consecutive chunks to modules (disjoint node sets within a permutation,
// like the reference's label shuffle), and evaluate all seven statistics.
//
//   nulls:    n_perm × n_mod × 7, row-major (caller-allocated)
//   seed:     RNG stream id; permutation p uses splitmix64(seed ^ global p),
//             so results are invariant to n_threads and call chunking.
//   progress: optional counter incremented once per finished permutation
//             (atomic; caller may poll from another thread)
//   cancel:   optional flag; when *cancel != 0 workers stop claiming new
//             permutations (cooperative Ctrl-C, SURVEY.md §5)
// Returns the number of permutations completed (== n_perm unless cancelled;
// when cancelled, completed rows are a PREFIX of the range — workers claim
// indices in order and the return value is the count of finished prefix
// rows).
// ---------------------------------------------------------------------------
long long nr_null(const double* tcorr, const double* tnet,
                  const double* tdata, int n, int s, const int* pool,
                  int pool_size, const int* sizes, int n_mod,
                  const double* dcorr_cat, const double* ddeg_cat,
                  const double* dcontrib_cat, long long n_perm,
                  long long perm_offset, unsigned long long seed,
                  int n_threads, double* nulls, long long* progress,
                  const int* cancel) {
  auto disc = make_disc(dcorr_cat, ddeg_cat, dcontrib_cat, sizes, n_mod);
  int max_m = 1;
  long long total_assigned = 0;
  for (int k = 0; k < n_mod; ++k) {
    max_m = std::max(max_m, sizes[k]);
    total_assigned += sizes[k];
  }
  if (total_assigned > pool_size) return -1;  // caller bug: pool too small

  if (n_threads <= 0)
    n_threads = (int)std::max(1u, std::thread::hardware_concurrency());
  n_threads = (int)std::min<long long>(n_threads, std::max<long long>(1, n_perm));

  std::atomic<long long> next(0);
  std::atomic<long long> done(0);

  auto worker = [&]() {
    Scratch sc;
    sc.reserve(max_m, s, pool_size);
    for (;;) {
      if (cancel && *cancel) break;
      const long long p = next.fetch_add(1, std::memory_order_relaxed);
      if (p >= n_perm) break;
      // counter-based per-permutation RNG (determinism contract above)
      std::mt19937_64 gen(splitmix64(seed ^ (0x5851F42D4C957F2DULL *
                                             (uint64_t)(perm_offset + p + 1))));
      std::memcpy(sc.perm.data(), pool, sizeof(int) * pool_size);
      // partial Fisher–Yates: only the first total_assigned draws are used
      for (long long i = 0; i < total_assigned; ++i) {
        const uint64_t j = (uint64_t)i + bounded_draw(gen, (uint64_t)(pool_size - i));
        std::swap(sc.perm[i], sc.perm[j]);
      }
      size_t off = 0;
      double* row = nulls + (size_t)p * n_mod * N_STATS;
      for (int k = 0; k < n_mod; ++k) {
        module_stats(disc[k], tcorr, tnet, tdata, n, s,
                     sc.perm.data() + off, sc, row + (size_t)k * N_STATS);
        off += sizes[k];
      }
      done.fetch_add(1, std::memory_order_relaxed);
      if (progress)
        __atomic_fetch_add(progress, 1, __ATOMIC_RELAXED);
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& th : threads) th.join();

  // Workers poll `cancel` only BEFORE claiming an index and always finish a
  // claimed permutation, so the completed rows are exactly the contiguous
  // prefix [0, done) — no holes.
  return done.load();
}

// ---------------------------------------------------------------------------
// nr_props — the observed network-properties entry (SURVEY.md §2.2
// "Observed network-properties entry"): for one dataset and one module
// index set, return weighted degree, node contribution, summary profile,
// coherence, and average edge weight. Data-less case: pass data=nullptr,
// contrib/profile outputs are left untouched and coherence is NaN.
// ---------------------------------------------------------------------------
void nr_props(const double* corr, const double* net, const double* data,
              int n, int s, const int* idx, int m, double* degree_out,
              double* contrib_out, double* profile_out, double* coherence_out,
              double* avg_weight_out) {
  (void)corr;
  Scratch sc;
  sc.reserve(m, s, 1);
  for (int i = 0; i < m; ++i) {
    const double* nrow = net + (size_t)idx[i] * n;
    double* ni = sc.net.data() + (size_t)i * m;
    for (int j = 0; j < m; ++j) ni[j] = nrow[idx[j]];
  }
  weighted_degree(sc.net.data(), m, degree_out);
  *avg_weight_out = avg_weight(sc.net.data(), m);
  *coherence_out = NAN;
  if (data != nullptr && s > 0) {
    for (int i = 0; i < s; ++i) {
      const double* drow = data + (size_t)i * n;
      double* zrow = sc.z.data() + (size_t)i * m;
      for (int j = 0; j < m; ++j) zrow[j] = drow[idx[j]];
    }
    standardize_cols(sc.z.data(), s, m);
    summary_profile(sc.z.data(), s, m, profile_out, sc.tmp.data());
    node_contribution(sc.z.data(), s, m, profile_out, contrib_out);
    double coh = 0.0;
    for (int j = 0; j < m; ++j) coh += contrib_out[j] * contrib_out[j];
    *coherence_out = m > 0 ? coh / m : NAN;
  }
}

// ABI version stamp so the Python wrapper can detect stale cached builds.
int nr_abi_version() { return 1; }

}  // extern "C"
