"""Sparse-adjacency kernels — the Config E path (BASELINE.json:11: 50k-cell
kNN graph, sparse adjacency, Leiden-cluster modules; SURVEY.md §7 step 5).

The reference has no sparse mode at all — its C++ core slices dense
``n × n`` matrices. At single-cell scale a dense adjacency is 10 GB while a
kNN graph is ``n × k`` with k ≈ 15–30, so the rebuild makes sparse a
first-class representation designed for XLA rather than adapting a
CSR/BCOO library format (SURVEY.md §7 "Hard parts": JAX sparse support is
limited — plan a gather-on-edge-list formulation):

- **Padded neighbor lists, static shapes.** The adjacency is ``nbr (n, k)``
  int32 neighbor ids and ``wgt (n, k)`` float32 weights, rows padded to the
  max degree with the sentinel id ``n`` and weight 0. Every kernel is then
  fixed-shape gathers + elementwise ops + reductions — no dynamic sparsity
  structure for XLA to choke on.
- **Membership by sort + searchsorted,** not an ``n``-length scatter mask:
  per (permutation, module) the candidate set is sorted once (``m log m``)
  and each gathered neighbor id binary-searched (``m·k·log m``), keeping
  the working set at ``O(m·k)`` instead of ``O(n)`` per instance — the
  difference between fitting a 64-permutation chunk in HBM or not at n=50k.
- **Correlation on the fly — or precomputed-sparse.** No ``n × n``
  correlation matrix ever exists: the per-module correlation submatrix is
  one MXU matmul of the gathered, standardized data slice (``zᵀz/(s-1)`` =
  exact Pearson) — or, when the user supplies a PRECOMPUTED sparse
  correlation in the same neighbor-list format, a membership scatter out of
  it (:func:`scatter_corr_submatrix`; the user's correlation is
  authoritative, matching the dense surface). Without data, a precomputed
  correlation keeps four statistics finite (avg.weight, cor.cor,
  cor.degree, avg.cor); with neither input only avg.weight/cor.degree are
  defined (documented deviation: the dense data-less variant has cor.cor
  because the user supplies a dense correlation matrix — at sparse scale
  that dense matrix is exactly what we refuse to materialize).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp

from . import stats as jstats
from .stats import DiscProps, _f32

_EPS = 1e-30

#: sentinel stored in padded neighbor / index slots (never a valid node id)
def _sentinel(n: int) -> int:
    return n


@dataclasses.dataclass(frozen=True)
class SparseAdjacency:
    """Symmetric sparse adjacency as padded neighbor lists (see module
    docstring). ``nbr[i]`` holds the neighbor ids of node ``i`` padded with
    the sentinel ``n``; ``wgt[i]`` the matching edge weights padded with 0.
    Self-loops are dropped on construction (the statistics exclude the
    diagonal, SURVEY.md §2.2)."""

    nbr: np.ndarray   # (n, k) int32
    wgt: np.ndarray   # (n, k) float32
    n: int

    @property
    def k(self) -> int:
        return self.nbr.shape[1]

    @property
    def nnz(self) -> int:
        return int((self.wgt != 0).sum())

    @classmethod
    def from_coo(
        cls, rows, cols, vals, n: int, symmetrize: bool = True
    ) -> "SparseAdjacency":
        """Build from COO triplets. ``symmetrize=True`` (default) unions the
        edge set with its transpose — pass each undirected edge once or in
        both directions. Duplicate entries for the same undirected edge (in
        either orientation) are resolved to the LAST one in input order, on
        the canonical ``(min(i,j), max(i,j))`` edge *before* mirroring — so
        both directions always agree and the adjacency stays symmetric even
        when conflicting reciprocal entries are given. With
        ``symmetrize=False`` the input must already contain both directions
        of every edge; per-direction duplicates resolve last-wins."""
        rows = np.asarray(rows, dtype=np.int64)
        cols = np.asarray(cols, dtype=np.int64)
        vals = np.asarray(vals, dtype=np.float64)
        if rows.shape != cols.shape or rows.shape != vals.shape:
            raise ValueError("rows/cols/vals must have identical shapes")
        if rows.size and (rows.min() < 0 or rows.max() >= n
                          or cols.min() < 0 or cols.max() >= n):
            raise ValueError(f"COO indices out of range for n={n}")
        keep = (rows != cols) & (vals != 0)
        rows, cols, vals = rows[keep], cols[keep], vals[keep]
        if symmetrize:
            # canonicalize to (lo, hi) and dedupe BEFORE mirroring: a stable
            # sort keeps input order within each edge group, so the last
            # occurrence wins regardless of orientation — (i,j)=a alongside
            # (j,i)=b can then never produce an asymmetric adjacency
            lo, hi = np.minimum(rows, cols), np.maximum(rows, cols)
            order = np.lexsort((hi, lo))
            lo, hi, vals = lo[order], hi[order], vals[order]
            last = np.ones(lo.size, dtype=bool)
            if lo.size > 1:
                last[:-1] = (lo[:-1] != lo[1:]) | (hi[:-1] != hi[1:])
            lo, hi, vals = lo[last], hi[last], vals[last]
            rows, cols = np.concatenate([lo, hi]), np.concatenate([hi, lo])
            vals = np.concatenate([vals, vals])
        # dedupe (i, j): later entries overwrite earlier
        order = np.lexsort((cols, rows))
        rows, cols, vals = rows[order], cols[order], vals[order]
        uniq = np.ones(rows.size, dtype=bool)
        if rows.size > 1:
            uniq[:-1] = (rows[:-1] != rows[1:]) | (cols[:-1] != cols[1:])
        rows, cols, vals = rows[uniq], cols[uniq], vals[uniq]

        counts = np.bincount(rows, minlength=n)
        k = max(int(counts.max(initial=0)), 1)
        nbr = np.full((n, k), _sentinel(n), dtype=np.int32)
        wgt = np.zeros((n, k), dtype=np.float32)
        # rows are lexsorted, so each row's entries are consecutive: the slot
        # of entry t is t - start(row) — vectorized (a per-edge Python loop
        # is interpreter-bound at the 50k-node/1.5M-edge Config E scale)
        starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
        slot = np.arange(rows.size) - starts[rows]
        nbr[rows, slot] = cols
        wgt[rows, slot] = vals
        return cls(nbr=nbr, wgt=wgt, n=n)

    @classmethod
    def from_dense(cls, mat, tol: float = 0.0) -> "SparseAdjacency":
        """Sparsify a dense symmetric adjacency (|entry| > tol kept)."""
        mat = np.asarray(mat, dtype=np.float64)
        if mat.ndim != 2 or mat.shape[0] != mat.shape[1]:
            raise ValueError(f"adjacency must be square, got {mat.shape}")
        if not np.allclose(mat, mat.T, atol=1e-8):
            raise ValueError("adjacency must be symmetric")
        rows, cols = np.nonzero(np.abs(mat) > tol)
        return cls.from_coo(
            rows, cols, mat[rows, cols], mat.shape[0], symmetrize=False
        )

    @classmethod
    def from_scipy(cls, mat, symmetrize: bool = True) -> "SparseAdjacency":
        """Build from any ``scipy.sparse`` matrix (the lingua franca of
        single-cell kNN graphs, e.g. ``adata.obsp['connectivities']``).
        Directed kNN graphs are symmetrized by default (union with the
        transpose, conflicting reciprocal weights resolved per
        :meth:`from_coo`)."""
        try:
            from scipy import sparse as sp
        except Exception as e:  # pragma: no cover - scipy is baked in
            raise ImportError("from_scipy requires scipy") from e
        if not sp.issparse(mat):
            raise TypeError(
                f"from_scipy takes a scipy.sparse matrix, got {type(mat).__name__}"
            )
        if mat.shape[0] != mat.shape[1]:
            raise ValueError(f"adjacency must be square, got {mat.shape}")
        coo = mat.tocoo()
        # scipy semantics SUM duplicate COO entries; from_coo resolves
        # last-wins — collapse first so the weights match what the user's
        # matrix means
        coo.sum_duplicates()
        return cls.from_coo(
            coo.row, coo.col, coo.data, mat.shape[0], symmetrize=symmetrize
        )

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.n, self.n), dtype=np.float64)
        rows = np.repeat(np.arange(self.n), self.k)
        cols = self.nbr.reshape(-1)
        vals = self.wgt.reshape(-1).astype(np.float64)
        keep = cols < self.n
        out[rows[keep], cols[keep]] = vals[keep]
        return out


# ---------------------------------------------------------------------------
# JAX kernels (single module; batch with vmap)
# ---------------------------------------------------------------------------

def sparse_module_topology(
    nbr_rows: jnp.ndarray,   # (m, k) gathered neighbor ids
    wgt_rows: jnp.ndarray,   # (m, k) gathered weights
    idx: jnp.ndarray,        # (m,) padded module node ids
    w: jnp.ndarray,          # (m,) 0/1 validity mask
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Within-module average edge weight and weighted degree from padded
    neighbor lists. Membership of each neighbor in the module's node set is
    tested by binary search against the sorted valid ids (module docstring).
    Matches the dense kernels exactly on densified graphs: absent edges are
    zeros in both representations, and the denominator is all ordered valid
    pairs ``m·(m-1)`` — not just existing edges."""
    m = idx.shape[-1]
    big = jnp.int32(np.iinfo(np.int32).max)
    sidx = jnp.sort(jnp.where(w > 0, idx, big))
    pos = jnp.clip(jnp.searchsorted(sidx, nbr_rows), 0, m - 1)
    member = (jnp.take(sidx, pos) == nbr_rows) & (nbr_rows != idx[:, None])
    mw = _f32(wgt_rows) * member * _f32(w)[:, None]
    degree = jnp.sum(mw, axis=-1) * _f32(w)
    mv = jnp.sum(_f32(w), axis=-1)
    avg_weight = jnp.sum(degree, axis=-1) / jnp.maximum(mv * (mv - 1.0), _EPS)
    return avg_weight, degree


def scatter_corr_submatrix(
    nbr_rows: jnp.ndarray,   # (m, k) gathered correlation-graph neighbor ids
    wgt_rows: jnp.ndarray,   # (m, k) gathered correlation values
    idx: jnp.ndarray,        # (m,) padded module node ids
    w: jnp.ndarray,          # (m,) 0/1 validity mask
) -> jnp.ndarray:
    """Module-order (m, m) correlation submatrix from a PRECOMPUTED sparse
    correlation in neighbor-list format (VERDICT r1 item 8: restores
    cor.cor/avg.cor for topology-only users whose correlation was sparsified
    upstream, e.g. alongside the kNN graph). Reuses the sort + searchsorted
    membership machinery (module docstring); member hits scatter-add into
    the submatrix at their *module-order* positions (rank → original
    position via the argsort permutation), absent pairs stay 0 — the same
    convention the adjacency kernels use for absent edges. Output is
    multiplied by the off-diagonal pair mask (the
    :func:`netrep_tpu.ops.stats.stats_from_parts` input form)."""
    import jax

    m = idx.shape[-1]
    big = jnp.int32(np.iinfo(np.int32).max)
    keyed = jnp.where(w > 0, idx, big)
    order = jnp.argsort(keyed)                    # rank r ← original order[r]
    sidx = jnp.take(keyed, order)
    pos = jnp.clip(jnp.searchsorted(sidx, nbr_rows), 0, m - 1)
    member = (
        (jnp.take(sidx, pos) == nbr_rows)
        & (nbr_rows != idx[:, None])
        & (w[:, None] > 0)
    )
    cols = jnp.take(order, pos)                   # module-order column
    rows_i = jax.lax.broadcasted_iota(jnp.int32, nbr_rows.shape, 0)
    sub = jnp.zeros((m, m), jnp.float32).at[
        rows_i, jnp.where(member, cols, m)        # m = out-of-bounds: dropped
    ].add(jnp.where(member, _f32(wgt_rows), 0.0), mode="drop")
    return sub * jstats.offdiag_mask(w)


def corr_from_zdata(zdata: jnp.ndarray, n_samples: int, w: jnp.ndarray) -> jnp.ndarray:
    """Exact Pearson correlation submatrix from a standardized (ddof=1)
    masked data slice: ``zᵀz/(s-1)``, multiplied by the off-diagonal pair
    mask (the form :func:`netrep_tpu.ops.stats.stats_from_parts` expects).
    This is the on-the-fly replacement for gathering out of an ``n × n``
    correlation matrix."""
    corr = jnp.matmul(
        jnp.swapaxes(zdata, -1, -2), zdata, preferred_element_type=jnp.float32
    ) / jnp.maximum(n_samples - 1, 1)
    return corr * jstats.offdiag_mask(w)


def sparse_gather_and_stats(
    disc: DiscProps,
    idx: jnp.ndarray,              # (m,) int32 padded test-node ids
    nbr: jnp.ndarray,              # (n, k) neighbor ids
    wgt: jnp.ndarray,              # (n, k) weights
    test_data: jnp.ndarray | None,  # (n_samples, n)
    corr_nbr: jnp.ndarray | None = None,  # (n, k_c) sparse-corr neighbor ids
    corr_wgt: jnp.ndarray | None = None,  # (n, k_c) sparse-corr values
    n_iter: int = 60,
    summary_method: str = "power",
) -> jnp.ndarray:
    """The sparse counterpart of :func:`netrep_tpu.ops.stats.gather_and_stats`
    — the per-permutation unit of work for Config E. Gathers ``O(m·k)``
    adjacency rows plus (optionally) an ``(s, m)`` data slice, never touching
    anything ``O(n²)``. ``idx`` padded slots must hold in-range row ids (the
    mask removes their influence); batching over permutations/modules is
    ``vmap`` of this function.

    Correlation precedence (mirrors the dense surface where the user's
    ``correlation`` argument is authoritative): a PRECOMPUTED sparse
    correlation (``corr_nbr``/``corr_wgt``) feeds the correlation statistics
    when given; otherwise they derive from ``test_data`` on the fly; with
    neither they are NaN. With a precomputed correlation and no data,
    ``avg.cor`` is also computed (its inputs are purely correlations) —
    four finite statistics for topology-only users (VERDICT r1 item 8)."""
    w = disc.mask
    safe_idx = jnp.where(w > 0, idx, 0)  # pad rows gather row 0, masked out
    nbr_rows = jnp.take(nbr, safe_idx, axis=0)
    wgt_rows = jnp.take(wgt, safe_idx, axis=0)
    avg_weight, degree = sparse_module_topology(nbr_rows, wgt_rows, idx, w)

    if test_data is not None:
        sub = jnp.take(test_data, safe_idx, axis=-1)
        zdata = jstats.standardize_masked(sub, w)
    else:
        zdata = None
    if corr_nbr is not None:
        corr = scatter_corr_submatrix(
            jnp.take(corr_nbr, safe_idx, axis=0),
            jnp.take(corr_wgt, safe_idx, axis=0),
            idx, w,
        )
    elif zdata is not None:
        corr = corr_from_zdata(zdata, test_data.shape[-2], w)
    else:
        corr = None

    out = jstats.stats_from_parts(
        disc, avg_weight, degree, corr, zdata,
        n_iter=n_iter, summary_method=summary_method,
    )
    if corr is not None and zdata is None:
        # avg.cor (STAT_NAMES index 5) needs only correlations; the shared
        # stats_from_parts keeps the dense data-less convention (NaN, as the
        # reference's data-less variant documents) so the sparse
        # precomputed-correlation case patches it in here.
        pair = jstats.offdiag_mask(w)
        npair = jnp.maximum(jnp.sum(pair, axis=(-1, -2)), 1e-30)
        avg_cor = jnp.sum(disc.sign_corr * corr, axis=(-1, -2)) / npair
        out = out.at[..., 5].set(avg_cor)
    return out


def make_disc_props_sparse(
    adj_nbr: jnp.ndarray,
    adj_wgt: jnp.ndarray,
    data: jnp.ndarray | None,      # (n_samples, n) or None
    idx_pad: jnp.ndarray,          # (K, cap) padded discovery ids
    mask: jnp.ndarray,             # (K, cap)
    corr_nbr: jnp.ndarray | None = None,  # (n, k_c) sparse-corr neighbors
    corr_wgt: jnp.ndarray | None = None,  # (n, k_c) sparse-corr values
    summary_method: str = "eigh",
) -> DiscProps:
    """Discovery-side fixed properties for a bucket of modules on a sparse
    discovery network: degree from neighbor lists, correlation submatrix
    from the PRECOMPUTED sparse correlation when given (the user's
    correlation is authoritative, as on the dense surface) else from the
    data slice on the fly; node contributions from data. Runs once per
    pair, outside the hot loop (SURVEY.md §3.1)."""
    import jax

    w = _f32(mask)
    safe_idx = jnp.where(mask > 0, idx_pad, 0)
    nbr_rows = jnp.take(adj_nbr, safe_idx, axis=0)   # (K, cap, k)
    wgt_rows = jnp.take(adj_wgt, safe_idx, axis=0)
    _avg, degree = jax.vmap(sparse_module_topology)(
        nbr_rows, wgt_rows, idx_pad, mask
    )
    if data is not None:
        # (s, K, cap) → (K, s, cap)
        sub = jnp.moveaxis(jnp.take(data, safe_idx, axis=-1), 1, 0)
        zdata = jstats.standardize_masked(sub, w)
        prof = jstats.summary_profile_masked(zdata, w, method=summary_method)
        contrib = jstats.node_contribution_masked(zdata, prof, w)
    else:
        zdata = None
        contrib = jnp.zeros_like(degree)
    if corr_nbr is not None:
        corr = jax.vmap(scatter_corr_submatrix)(
            jnp.take(corr_nbr, safe_idx, axis=0),
            jnp.take(corr_wgt, safe_idx, axis=0),
            idx_pad, mask,
        )
    elif zdata is not None:
        corr = corr_from_zdata(zdata, data.shape[-2], w)
    else:
        corr = jnp.zeros(idx_pad.shape + idx_pad.shape[-1:], dtype=jnp.float32)
    return DiscProps(
        corr=corr,
        sign_corr=jnp.sign(corr),
        degree=degree,
        contrib=contrib,
        sign_contrib=jnp.sign(contrib),
        mask=w,
    )
