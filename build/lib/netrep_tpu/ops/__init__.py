"""Compute kernels: NumPy oracle semantics (`oracle`), JAX masked statistic
kernels (`stats`), and exact permutation p-values (`pvalues`)."""
