"""Shared utilities: engine configuration (`config`)."""
